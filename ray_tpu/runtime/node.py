"""Node/process orchestration: spawning GCS and raylet daemons.

Analog of /root/reference/python/ray/_private/node.py (start_head_processes
:1045, start_ray_processes :1083) and services.py (start_gcs_server :1200,
start_raylet :1273): the head starts a GCS subprocess then a raylet
subprocess; worker nodes start just a raylet pointed at the head's GCS.
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional, Tuple

from ray_tpu._private.config import CONFIG
from ray_tpu._private.logging_utils import get_logger

logger = get_logger("node")


_session_seq = itertools.count()


def new_session_dir() -> str:
    """Unique per call, even for back-to-back init()s in one process
    within one wall second.  Two clusters sharing a dir was the
    daemon-spawn startup-race flake: the second ``start_gcs`` read the
    FIRST (dead) GCS's leftover ``gcs_address.json`` and pointed its
    raylet at a dead port (connection refused at spawn), and the second
    GCS replayed the first's snapshot/WAL as its own state.  The
    raylet address files already carried a microsecond suffix for
    exactly this collision — the session dir itself needed it too."""
    base = os.path.join("/tmp", "ray_tpu_sessions")
    os.makedirs(base, exist_ok=True)
    session = os.path.join(
        base, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{os.getpid()}"
              f"_{next(_session_seq)}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _wait_address_file(path: str, proc: subprocess.Popen,
                       timeout: float = 30.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except (ValueError, OSError):
                pass
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon exited with code {proc.returncode} before "
                f"publishing {path}")
        time.sleep(0.02)
    raise TimeoutError(f"daemon did not publish {path}")


def package_pythonpath() -> str:
    """PYTHONPATH that makes ray_tpu importable in child processes."""
    import ray_tpu
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    if pkg_root in existing.split(os.pathsep):
        return existing
    return pkg_root + (os.pathsep + existing if existing else "")


def _spawn(cmd, session_dir: str, name: str,
           env_overrides: Optional[Dict[str, str]] = None) -> subprocess.Popen:
    env = dict(os.environ)
    env["RAY_TPU_SYSTEM_CONFIG"] = CONFIG.overrides_env_blob()
    env["PYTHONPATH"] = package_pythonpath()
    env.update(env_overrides or {})
    log_prefix = os.path.join(session_dir, "logs", name)
    out_f = open(log_prefix + ".out", "ab")
    err_f = open(log_prefix + ".err", "ab")
    try:
        return subprocess.Popen(cmd, env=env, stdout=out_f, stderr=err_f)
    finally:
        out_f.close()
        err_f.close()


class NodeProcesses:
    """Daemons started by this process (head or worker node)."""

    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.raylet_proc: Optional[subprocess.Popen] = None
        self.gcs_address: Optional[Tuple[str, int]] = None
        self.raylet_address: Optional[Tuple[str, int]] = None
        self.node_id: Optional[str] = None
        self.store_path: Optional[str] = None
        atexit.register(self.stop)

    def start_gcs(self, port: int = 0) -> Tuple[str, int]:
        addr_file = os.path.join(self.session_dir, "gcs_address.json")
        # belt-and-braces vs the stale-address-file race: a leftover
        # file from an earlier GCS in this dir must never satisfy
        # _wait_address_file before the fresh daemon publishes its own
        try:
            os.remove(addr_file)
        except FileNotFoundError:
            pass
        self.gcs_proc = _spawn(
            [sys.executable, "-m", "ray_tpu.runtime.gcs",
             "--port", str(port),
             "--session-dir", self.session_dir,
             "--address-file", addr_file],
            self.session_dir, "gcs_server")
        info = _wait_address_file(addr_file, self.gcs_proc)
        self.gcs_address = (info["host"], info["port"])
        # advertise the most recent local session for address auto-discovery
        # (reference: session_latest symlink + RAY_ADDRESS resolution)
        try:
            latest = os.path.join("/tmp", "ray_tpu_sessions", "latest.json")
            tmp = latest + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"gcs_host": info["host"],
                           "gcs_port": info["port"],
                           "session_dir": self.session_dir}, f)
            os.replace(tmp, latest)
        except OSError:
            pass
        return self.gcs_address

    def start_raylet(self, gcs_address: Tuple[str, int],
                     resources: Optional[Dict[str, float]] = None,
                     object_store_memory: Optional[int] = None
                     ) -> Tuple[str, int]:
        addr_file = os.path.join(
            self.session_dir, f"raylet_address_{os.getpid()}_"
                              f"{int(time.time()*1e6)}.json")
        cmd = [sys.executable, "-m", "ray_tpu.runtime.raylet",
               "--gcs-host", gcs_address[0],
               "--gcs-port", str(gcs_address[1]),
               "--session-dir", self.session_dir,
               "--address-file", addr_file]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        self.raylet_proc = _spawn(cmd, self.session_dir, "raylet")
        info = _wait_address_file(addr_file, self.raylet_proc)
        self.raylet_address = (info["host"], info["port"])
        self.node_id = info["node_id"]
        self.store_path = info["store_path"]
        return self.raylet_address

    def stop(self) -> None:
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is not None:
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()
        self.raylet_proc = self.gcs_proc = None
