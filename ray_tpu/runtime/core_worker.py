"""Per-process runtime: task submission, object ownership, get/put/wait.

TPU-native analog of the reference CoreWorker
(/root/reference/src/ray/core_worker/core_worker.h:261): every driver and
worker embeds one.  It owns

  - the in-process memory store for inlined objects
    (store_provider/memory_store/memory_store.h:43),
  - the shm-store client for large objects (plasma_store_provider.h:88),
  - the ownership table: this process owns the objects its tasks return
    (reference_count.h:61 ownership model — the owner records locations and
    serves gets; no central object table),
  - the lease-based task submitter
    (transport/direct_task_transport.h:57 — lease a worker per scheduling
    key from the raylet, push tasks directly, return when idle), and
  - actor handles with per-actor ordered submission queues
    (transport/direct_actor_task_submitter.h:67 — sequence numbers,
    resubmit on restart).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu.exceptions import SchedulingError
from ray_tpu._private import rpc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import serialization as ser
from ray_tpu._private import transfer
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.logging_utils import get_logger
from ray_tpu.runtime.gcs import ALIVE, DEAD, GcsClient, RESTARTING
from ray_tpu.runtime.object_store import SharedMemoryStore

# tracing_helper lives under ray_tpu.util, whose __init__ imports back
# into core_worker (placement_group) — resolved lazily, cached
_trh = None


def _tracing():
    global _trh
    if _trh is None:
        from ray_tpu.util.tracing import tracing_helper
        _trh = tracing_helper
    return _trh

logger = get_logger("core_worker")

_INLINE_MAX = None  # resolved lazily from CONFIG

# hot-path telemetry (docs/observability.md): bound once, attribute
# arithmetic per record, no-ops when RAY_TPU_TELEMETRY=0.  _TELEMETRY
# guards the sites with real bookkeeping (the _task_t0 stamp dict),
# so the kill switch removes that cost too.
_TELEMETRY = rtm.enabled()
_M_PUT = rtm.histogram("ray_tpu_put_ms", "ray.put latency (ms)")
_M_GET = rtm.histogram("ray_tpu_get_ms", "per-ref ray.get latency (ms)")
_M_TASK_E2E = rtm.histogram(
    "ray_tpu_task_e2e_ms",
    "task submit -> terminal reply latency at the owner (ms)")
_M_PUSH_BATCH = rtm.histogram(
    "ray_tpu_task_push_batch_size",
    "task specs coalesced per push_tasks frame",
    boundaries=rtm.COUNT_BOUNDARIES)
_M_QUEUE_WAIT = rtm.histogram(
    "ray_tpu_task_queue_wait_ms",
    "task submit -> dispatch-to-worker wait at the owner (ms); the "
    "metric twin of the timeline's SUBMITTED->RUNNING queue_wait slice")
_M_STREAM_ITEMS = rtm.counter(
    "ray_tpu_stream_items_total",
    "streaming-generator items reported to this owner")
_M_STREAM_STALLS = rtm.counter(
    "ray_tpu_stream_backpressure_stalls_total",
    "item reports parked for backpressure (consumer behind producer)")
_M_STREAM_PARKED = rtm.histogram(
    "ray_tpu_stream_parked_report_ms",
    "time an item report spent parked before consumption released it")
_M_FETCH_LOCAL = rtm.counter(
    "ray_tpu_fetch_local_hits_total",
    "borrowed-object fetches served from local shm (prefetch/locality "
    "hits: the bytes were already here)")
_M_FETCH_REMOTE = rtm.counter(
    "ray_tpu_fetch_remote_pulls_total",
    "borrowed-object fetches that had to pull from a remote node")
_M_ACTOR_SUBMITS = rtm.counter(
    "ray_tpu_actor_tasks_submitted_total",
    "classic actor-task submissions from this process; a compiled-DAG "
    "hot loop must NOT move this (the zero-submission contract the "
    "pipeline runner asserts, docs/compiled_dag.md)")


class ObjectRef:
    """Handle to a future object.  Embeds the owner's serving address so any
    borrower can reach the owner directly (ownership-based directory,
    cf. ownership_based_object_directory.h)."""

    __slots__ = ("id", "owner_addr", "_worker", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: Tuple[str, int],
                 worker: Optional["CoreWorker"] = None):
        self.id = object_id
        self.owner_addr = tuple(owner_addr)
        self._worker = worker
        if worker is not None:
            worker._ref_created(object_id)

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def __repr__(self) -> str:
        return f"ObjectRef({self.id.hex()[:16]})"

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __del__(self):
        w = self._worker
        if w is not None:
            w._ref_deleted(self.id)

    def __reduce__(self):
        # crossing process boundaries drops the local refcount hook; the
        # receiver re-binds to its own core worker on use
        return (_rebuild_ref, (self.id.binary(), self.owner_addr))

    def future(self):
        """concurrent.futures-style accessor used by library code."""
        from concurrent.futures import Future
        f: Future = Future()
        def _poll():
            try:
                f.set_result(get_global_worker().get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                f.set_exception(e)
        threading.Thread(target=_poll, daemon=True).start()
        return f


def _rebuild_ref(id_bytes: bytes, owner_addr) -> "ObjectRef":
    worker = _global_worker
    return ObjectRef(ObjectID(id_bytes), tuple(owner_addr), worker)


def num_return_slots(num_returns) -> int:
    """Owner-side return slots: "dynamic" and "streaming" reserve one
    (the generator / completion-sentinel slot)."""
    return 1 if num_returns in ("dynamic", "streaming") else num_returns


_STRING_NUM_RETURNS = ("dynamic", "streaming")


def normalize_num_returns(value, *, where: str = "num_returns"):
    """Single validation point for the num_returns modes shared by
    RemoteFunction and ActorMethod: a non-negative int, "dynamic"
    (refs materialize when the whole task finishes), or "streaming"
    (per-yield delivery through a StreamingObjectRefGenerator)."""
    if value in _STRING_NUM_RETURNS:
        return value
    if isinstance(value, bool) or not isinstance(value, int):
        raise TypeError(
            f"{where} must be a non-negative int, \"dynamic\" or "
            f"\"streaming\"; got {value!r}")
    if value < 0:
        raise ValueError(f"{where} must be >= 0; got {value}")
    return value


class ObjectRefGenerator:
    """The value of a ``num_returns="dynamic"`` task: an iterable of the
    refs the task produced, one per yielded item (cf. reference
    ObjectRefGenerator, _raylet.pyx:169)."""

    def __init__(self, refs: List["ObjectRef"]):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self):
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]

    def __repr__(self):
        return f"ObjectRefGenerator({len(self._refs)} refs)"


class _StreamState:
    """Owner-side record of one in-flight ``num_returns="streaming"``
    task (the generator table entry).  Item indexes may arrive in any
    order (reports and the task-level completion ride different
    connections); the consumer always advances strictly by index."""

    __slots__ = ("task_binary", "bp", "cv", "arrived", "consumed", "total",
                 "failed", "parked", "closed", "max_unconsumed", "waiters")

    def __init__(self, task_binary: bytes, bp: int):
        self.task_binary = task_binary
        self.bp = bp                      # backpressure window (<=0: off)
        self.cv = threading.Condition()
        self.arrived: set = set()         # reported, not yet consumed
        self.consumed = 0                 # next index the consumer wants
        self.total: Optional[int] = None  # num_items once complete
        self.failed = False               # terminal error stored in slot 0
        self.closed = False               # consumer dropped the generator
        # event-driven consumers (async __anext__): callbacks fired on
        # the next state change instead of a thread blocking on cv —
        # 1000 concurrent awaited streams cost 0 threads, not 1000
        self.waiters: List = []
        # (index, Deferred, t_parked) item reports parked for
        # backpressure: each resolves when ITS item is consumed, so the
        # producer's unacked window is exactly the unconsumed in-flight
        # count; t_parked feeds the parked-report-time histogram
        self.parked: List[tuple] = []
        self.max_unconsumed = 0           # high-water mark (tests/stats)


class _StreamExhausted:
    """Internal sentinel returned by CoreWorker._stream_next at end of
    stream (StopIteration must not cross executor/coroutine seams)."""


class StreamingObjectRefGenerator:
    """The value of a ``num_returns="streaming"`` task/actor call: each
    ``__next__``/``__anext__`` blocks until the NEXT yielded item has
    been reported by the executing worker and returns its ObjectRef —
    the first item is observable while the task is still running, unlike
    "dynamic" where refs appear only at task completion.  Consuming an
    item acks it to the producer (releasing backpressure credit).

    Not serializable: the stream is owned by the submitting process."""

    def __init__(self, worker: "CoreWorker", state: _StreamState,
                 ref: "ObjectRef"):
        self._worker = worker
        self._state = state
        self._ref = ref

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        out = self._worker._stream_next(self._state, self._ref)
        if out is _StreamExhausted:
            raise StopIteration
        return out

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        """Event-driven await: non-blocking claim attempts with a
        state-change waiter between them — no executor thread parks for
        the wait, so thousands of concurrently-awaited streams coexist
        on one event loop (the serve_disagg 1k-connection harness
        shape; the old executor hop capped concurrency at the thread
        pool size)."""
        import asyncio
        loop = asyncio.get_running_loop()
        while True:
            out = self._worker._stream_try_next(self._state, self._ref)
            if out is _StreamExhausted:
                raise StopAsyncIteration
            if out is not None:
                return out
            fut = loop.create_future()

            def _wake(_loop=loop, _fut=fut):
                _loop.call_soon_threadsafe(
                    lambda: _fut.done() or _fut.set_result(None))

            self._worker._stream_add_waiter(self._state, _wake)
            await fut

    def completed(self) -> "ObjectRef":
        """Ref that resolves when the whole generator task finishes:
        to the full ObjectRefGenerator of item refs on success, to the
        task's error on failure (the ``ray.get``-able completion
        sentinel)."""
        return self._ref

    def close(self) -> None:
        """Cancel the stream: parked producer reports are released with
        a cancel verdict (the worker stops iterating the generator) and
        unconsumed item objects are freed."""
        self._worker._close_stream(self._state)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __reduce__(self):
        raise TypeError(
            "StreamingObjectRefGenerator is not serializable; it can "
            "only be consumed by the process that submitted the task")

    def __repr__(self):
        st = self._state
        return (f"StreamingObjectRefGenerator(consumed={st.consumed}, "
                f"total={st.total})")


_global_worker: Optional["CoreWorker"] = None


def get_global_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def set_global_worker(worker: Optional["CoreWorker"]) -> None:
    global _global_worker
    _global_worker = worker


_cb_queue: "SimpleQueue" = None
_cb_lock = threading.Lock()


def _dispatch_callback(cb) -> None:
    """Ready callbacks run on one dedicated dispatcher thread, never on
    the thread that called set(): reply-processing paths hold _owned_lock
    when entries become ready, and a callback that blocked (or re-entered
    a CoreWorker API) there would stall every get/put/submit."""
    global _cb_queue
    if _cb_queue is None:
        with _cb_lock:
            if _cb_queue is None:
                from queue import SimpleQueue
                q = SimpleQueue()

                def loop():
                    while True:
                        f = q.get()
                        try:
                            f()
                        except Exception:
                            logger.exception("object ready callback failed")

                threading.Thread(target=loop, daemon=True,
                                 name="ready-callbacks").start()
                _cb_queue = q
    _cb_queue.put(cb)


class _NotifyingEvent:
    """threading.Event + ready callbacks, fired exactly once on set().
    Library code (Serve handles, async bridges) registers callbacks
    instead of polling wait() loops — the reference's task-completion
    callback path in core_worker's TaskManager. Callbacks are invoked on
    a shared dispatcher thread, not the setter's thread."""

    __slots__ = ("_ev", "_cbs", "_lock")

    def __init__(self):
        self._ev = threading.Event()
        self._cbs: List = []
        self._lock = threading.Lock()

    def set(self) -> None:
        with self._lock:
            self._ev.set()
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            _dispatch_callback(cb)

    def add_callback(self, cb) -> bool:
        """Register cb to run on set(); returns False (not registered)
        when already set — caller invokes it directly."""
        with self._lock:
            if self._ev.is_set():
                return False
            self._cbs.append(cb)
            return True

    def clear(self) -> None:
        with self._lock:
            self._ev.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)

    def is_set(self) -> bool:
        return self._ev.is_set()


class _OwnedObject:
    __slots__ = ("state", "data", "error", "locations", "event", "refcount",
                 "task_spec", "dynamic_children", "recovering", "size",
                 "last_lost_node", "recon_attempts", "evac_tried")

    def __init__(self):
        self.state = "pending"       # pending | ready
        self.data: Optional[bytes] = None     # serialized inline payload
        self.error = 0
        self.locations: set = set()  # node_id hex with a shm copy
        self.size = 0                # serialized bytes of a shm copy
        #   (0 = inline or unknown); feeds locality-aware lease hints
        self.event = _NotifyingEvent()
        self.refcount = 0
        # lineage for reconstruction: {"spec","resources","key",
        # "retries_left","strategy","env"} shared across sibling slots
        self.task_spec: Optional[dict] = None
        # sub-object ids of a num_returns="dynamic" task: freed with slot 0
        # unless a deserialized generator bound its own refs to them
        self.dynamic_children: Optional[list] = None
        # a _recover_or_fail thread is resolving this entry: borrowers
        # polling every 10 ms must not spawn redundant ones
        self.recovering = False
        # the last dead node pruned from ``locations``: when lineage is
        # exhausted, ObjectLostError names this node's crash dossier
        # (docs/fault_tolerance.md)
        self.last_lost_node: Optional[str] = None
        # lineage resubmits charged to THIS object, bounded by
        # object_reconstruct_max_attempts on top of the task's own
        # retry budget (a flapping node must converge, not loop)
        self.recon_attempts = 0
        # evac hints already followed by borrower-driven recovery: a
        # stale hint (landing node dropped the copy) must be consulted
        # once, not poll-looped forever by _recover_or_fail
        self.evac_tried: Optional[set] = None


# Pull admission control lives with the data-plane engine now
# (_private/transfer.py); the name stays importable here for callers and
# tests that treat it as part of the core worker's surface.
_PullBudget = transfer.PullBudget


class _Lease:
    __slots__ = ("lease_id", "worker_id", "address", "conn", "key",
                 "granting_addr", "pending", "plock")

    def __init__(self, key, grant, conn):
        self.key = key
        self.lease_id = grant["lease_id"]
        self.worker_id = grant["worker_id"]
        self.address = tuple(grant["address"])
        self.granting_addr = grant.get("granting_addr")  # None == local
        self.conn = conn
        # task_id -> (spec, retries) of every unresolved spec pushed on
        # this lease, in send order.  Resolution pops exactly once, under
        # plock, from whichever arrives first: the worker's streamed
        # task_done push (early, mid-frame) or the batch ack (authoritative
        # backstop); on connection death the leftovers are the unexecuted
        # tail (first entry = the spec that was executing).
        self.pending: Dict[bytes, tuple] = {}
        self.plock = threading.Lock()


class CoreWorker:
    # class-level defaults: the lease loop's queue-wait telemetry reads
    # these dicts, and test doubles that borrow the loop with a minimal
    # __init__ must see an (empty) mapping, not an AttributeError.
    # Real instances shadow them with their own dicts in __init__.
    # _task_t0 feeds the e2e histogram (popped at the terminal reply);
    # _task_tq feeds queue-wait and is popped at FIRST dispatch, so a
    # retry requeued after a worker death is never re-observed with the
    # original submit stamp (that sample would include the first
    # attempt's execution time).
    _task_t0: Dict[bytes, float] = {}
    _task_tq: Dict[bytes, float] = {}

    def _init_submitter_state(self) -> None:
        """Every field the task-submission machinery reads: the lease
        loops (``_enqueue_task``/``_lease_request_loop``/
        ``_lease_worker_loop``), spillback + locality hints
        (``_lease_with_spillback``/``_arg_hints``), and ownership
        bookkeeping.  The scripted-peer harnesses (tests/test_rpc.py,
        tests/test_scripted_peers.py) construct owners that skip
        ``CoreWorker.__init__`` and call THIS instead — a new submitter
        field initialized inline in ``__init__`` silently breaks that
        tier with an AttributeError swallowed on a lease thread, so add
        it here.
        """
        self._owned: Dict[ObjectID, _OwnedObject] = {}
        self._owned_lock = threading.RLock()  # ObjectRef ctor re-enters
        # strong refs to task-argument ObjectRefs, held until the task using
        # them completes (otherwise the owner may free the object before the
        # executing worker fetches it)
        self._arg_refs: Dict[bytes, list] = {}
        # task submission state: per scheduling key a FIFO of pending specs
        # and a set of leased workers that pull from it (cf. reference
        # OnWorkerIdle, direct_task_transport.cc:174 — tasks pipeline onto
        # leased workers; at most one lease request in flight per key,
        # RequestNewWorkerIfNeeded :325)
        self._sched: Dict[str, Dict[str, Any]] = {}
        self._sched_lock = threading.Lock()
        # wakes idle keepalive leases when new work lands on their key
        self._sched_cv = threading.Condition(self._sched_lock)
        # task binary -> remaining OOM-kill retries (separate budget from
        # max_retries; reference task_oom_retries)
        self._oom_retries: Dict[bytes, int] = {}
        self._node_table: Dict[str, Dict] = {}
        self._shutdown = threading.Event()
        # submit-time monotonic stamps: e2e latency + first-dispatch wait
        self._task_t0: Dict[bytes, float] = {}
        self._task_tq: Dict[bytes, float] = {}

    def __init__(self, *, mode: str, gcs_address: Tuple[str, int],
                 raylet_address: Tuple[str, int], store_path: str,
                 node_id: str, job_id: Optional[JobID] = None,
                 worker_id: Optional[WorkerID] = None,
                 session_dir: str = "", host: str = "127.0.0.1"):
        global _INLINE_MAX
        _INLINE_MAX = CONFIG.inline_object_max_bytes
        self.mode = mode  # "driver" | "worker"
        self.worker_id = worker_id or WorkerID.from_random()
        self.job_id = job_id or JobID.from_random()
        self.node_id = node_id
        self.session_dir = session_dir
        self.current_task_id = TaskID.from_random()  # driver root task
        self._put_counter = 0
        self._counter_lock = threading.Lock()

        self._init_submitter_state()
        self._memory_cache: Dict[ObjectID, Any] = {}   # deserialized values
        # insertion order of BORROWED cache entries only — the trim's
        # working set.  Owned entries leave via refcounting, so scanning
        # the whole cache for borrowed victims on every get was O(cache)
        # per call (quadratic across a big wave of gets) for zero
        # evictions.  Entries carry an insertion token matched against
        # _borrowed_tokens at trim time: a removal path (release_borrowed
        # etc.) drops the token, so a stale FIFO entry can never evict a
        # LIVE re-fetched value and release its active pins.
        self._borrowed_cache_order: deque = deque()   # (oid, token)
        self._borrowed_tokens: Dict[ObjectID, int] = {}
        self._borrowed_seq = itertools.count()
        self._pins: Dict[ObjectID, int] = {}   # local shm pins we hold
        self._pins_lock = threading.Lock()
        self._owner_conns = transfer.ConnCache()
        self._pull_budget = _PullBudget(CONFIG.pull_memory_cap_bytes)
        # bulk data plane (docs/object_transfer.md): pipelined multi-
        # source shm-direct pulls over the pooled connection cache
        self._puller: Optional[transfer.ObjectPuller] = None

        # streaming-generator table: task binary -> _StreamState for every
        # live num_returns="streaming" submission this process owns
        self._streams: Dict[bytes, _StreamState] = {}
        self._streams_lock = threading.Lock()

        self.store = SharedMemoryStore.attach(store_path)
        # report_generator_item only buffers + notifies (and may resolve
        # a parked Deferred, which just enqueues a reply frame): run it
        # inline on the reader thread — item delivery latency is the
        # time-to-first-token path.  report_object_location is a dict
        # update under _owned_lock.
        self._server = rpc.Server(
            self._handle_rpc, host=host,
            fast_methods=frozenset({"report_generator_item",
                                    "report_object_location"}))
        self.address = self._server.address
        self._puller = transfer.ObjectPuller(
            self.store, self._node_address, self._owner_conn,
            budget=self._pull_budget)

        self.gcs = GcsClient(gcs_address)
        self.raylet_addr = tuple(raylet_address)
        self._raylet = rpc.connect(self.raylet_addr)

        self._fn_cache: Dict[str, Any] = {}
        self._fn_key_by_id: Dict[int, str] = {}  # id(func) -> fn key
        self._fn_id_pins: Dict[int, Any] = {}    # keeps those ids stable

        # actor submission: per-actor ordered pipeline (a single sender
        # thread per actor allocates seqs in submission order and pipelines
        # calls; cf. CoreWorkerDirectActorTaskSubmitter's per-actor queues,
        # direct_actor_task_submitter.h:67).  A fresh connection starts a new
        # caller-stream with seq 0, so the actor-side queue never waits on
        # seqs that died with an old connection.
        self._actor_pipes: Dict[str, "_ActorPipe"] = {}
        self._actor_lock = threading.Lock()

        # job-level default runtime env (prepared descriptor) + prepare cache
        self.job_runtime_env: Optional[dict] = None
        self._runtime_env_cache: Dict[str, Optional[dict]] = {}

        # lineage ledger (reference: TaskManager lineage pinning,
        # task_manager.h:146 + object_recovery_manager.h:41): FIFO of task
        # binaries whose specs are pinned for reconstruction, bounded by
        # lineage_max_bytes; per-task slot sets so arg refs and specs are
        # dropped when the last return object is freed.
        self._lineage_bytes = 0
        self._lineage_order: deque = deque()
        self._lineage_meta: Dict[bytes, dict] = {}
        self._alive_cache: Tuple[float, set] = (0.0, set())

        # deferred remote frees: (node_hex, oid_binary) batched per node
        # every free_objects_period_ms (reference: plasma Delete batching)
        self._free_queue: List[Tuple[str, bytes]] = []
        self._free_cv = threading.Condition()
        self._free_thread = threading.Thread(target=self._free_loop,
                                             daemon=True)
        self._free_thread.start()

        from ray_tpu._private.task_events import TaskEventBuffer
        # only drivers know the true job id; worker-side CoreWorkers get a
        # random one, which must not overwrite the owner's in the task table
        self.events = TaskEventBuffer(
            self.gcs, job_id=self.job_id.hex() if mode == "driver" else "",
            node_id=node_id, worker_id=self.worker_id.hex())

        # runtime telemetry rides the GCS KV: bind this process's flusher
        # and the poll-time pin-count gauge (zero hot-path cost); both
        # are unhooked in shutdown() so this CoreWorker (and everything
        # its caches pin) stays collectable after ray_tpu.shutdown()
        self._pins_gauge_cb = lambda: sum(self._pins.values())
        rtm.gauge_callback("ray_tpu_shm_pins",
                           "shared-memory pins held by this process",
                           self._pins_gauge_cb)
        rtm.attach(self.gcs.kv_put,
                   ident=f"{mode}-{self.worker_id.hex()[:12]}")
        # cluster event plane + flight recorder (docs/observability.md):
        # lifecycle events batch to the GCS table; the in-memory ring
        # (incl. ring-only task breadcrumbs) is dumped to a per-worker
        # flight file each flush so the raylet can harvest it into a
        # crash dossier after this process dies
        import os
        from ray_tpu._private import cluster_events as cev
        flight = None
        if session_dir and mode == "worker":
            flight = os.path.join(
                session_dir, "logs",
                cev.flight_file_name(self.worker_id.hex()))
        self._events_recorder = cev.configure(
            sink=lambda evs: self.gcs.call(
                "report_cluster_events", {"events": evs}, timeout=5),
            source=mode, node_id=node_id,
            worker_id=self.worker_id.hex(),
            job_id=self.job_id.hex() if mode == "driver" else "",
            flight_path=flight)
        # distributed request tracing (docs/observability.md): bind this
        # process's span buffer; finished spans batch to the GCS span
        # table on the flusher thread, never on the request path
        from ray_tpu.util.tracing import tracing_helper as trh
        self._span_buffer = trh.configure(
            lambda spans: self.gcs.call(
                "report_spans", {"spans": spans}, timeout=5),
            node_id=node_id, worker_id=self.worker_id.hex(),
            source=mode)

    # ------------------------------------------------------------ lifecycle
    def shutdown(self) -> None:
        self._shutdown.set()
        # unhook telemetry publishing bound to this worker's GCS client
        # (a newer worker's attach/callback is left untouched)
        rtm.detach(self.gcs.kv_put)
        rtm.remove_gauge_callback("ray_tpu_shm_pins", self._pins_gauge_cb)
        from ray_tpu._private import cluster_events as cev
        cev.detach(self._events_recorder)
        from ray_tpu.util.tracing import tracing_helper as trh
        trh.detach(self._span_buffer)
        try:
            self.events.stop()
        except Exception:
            pass
        with self._sched_lock:
            leases = [l for s in self._sched.values() for l in s["leases"]]
            self._sched.clear()
            self._sched_cv.notify_all()  # abort idle keepalive waits
        for lease in leases:
            self._return_lease(lease)
        self._server.stop()
        with self._actor_lock:
            pipes = list(self._actor_pipes.values())
        for pipe in pipes:
            if pipe.conn is not None:
                pipe.conn.close()
        try:
            self._raylet.close()
        except Exception:
            pass
        try:
            self.gcs.close()
        except Exception:
            pass
        self.store.close()

    # ------------------------------------------------------- refcounting
    def _ref_created(self, oid: ObjectID) -> None:
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is not None:
                entry.refcount += 1

    def _ref_deleted(self, oid: ObjectID) -> None:
        if self._shutdown.is_set():
            return
        freed: List[Tuple[ObjectID, set]] = []
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is not None:
                entry.refcount -= 1
                if entry.refcount <= 0 and entry.state == "ready":
                    self._free_with_children_locked(oid, entry, freed)
        self._complete_frees(freed)

    def _free_with_children_locked(self, oid: ObjectID,
                                   entry: _OwnedObject,
                                   freed: list) -> None:
        self._free_entry_locked(oid, entry, freed)
        for child in entry.dynamic_children or ():
            child_entry = self._owned.get(child)
            if child_entry is not None and child_entry.refcount <= 0:
                # generator never deserialized: nothing else will ever
                # free these
                self._free_entry_locked(child, child_entry, freed)

    def _free_entry_locked(self, oid: ObjectID, entry: _OwnedObject,
                           freed: list) -> None:
        del self._owned[oid]
        self._memory_cache.pop(oid, None)
        freed.append((oid, set(entry.locations)))
        self._lineage_slot_freed_locked(oid)

    def _complete_frees(self, freed: List[Tuple[ObjectID, set]]) -> None:
        if self._shutdown.is_set():
            # the store mapping may already be closed: touching it from a
            # late reply/error path would fault, and the raylet reclaims
            # everything at session teardown anyway
            return
        for foid, locations in freed:
            self._release_pins(foid)
            # release the primary copies: local shm directly, remote nodes
            # (and any spilled files) via batched free_objects RPCs
            try:
                self.store.delete(foid)
            except Exception:
                pass
            # every location gets a free RPC — including our own node,
            # whose raylet may hold the copy as a spill file
            if locations:
                with self._free_cv:
                    for node_hex in locations:
                        self._free_queue.append((node_hex, foid.binary()))
                    self._free_cv.notify()

    def _lineage_slot_freed_locked(self, oid: ObjectID) -> None:
        """owned_lock held: drop a task's lineage (spec + pinned arg refs)
        once its last return object is freed."""
        if oid.is_put():
            return
        tb = oid.task_id().binary()
        meta = self._lineage_meta.get(tb)
        if meta is None:
            return
        meta["slots"].discard(oid)
        if any(o in self._owned for o in meta["slots"]):
            return
        self._lineage_meta.pop(tb, None)
        if not meta["evicted"]:
            self._lineage_bytes -= meta["size"]
        self._arg_refs.pop(tb, None)

    def _free_loop(self) -> None:
        period = CONFIG.free_objects_period_ms / 1000.0
        while not self._shutdown.is_set():
            with self._free_cv:
                if not self._free_queue:
                    self._free_cv.wait(timeout=1.0)
                batch, self._free_queue = self._free_queue, []
            if not batch:
                continue
            time.sleep(period)  # let more frees accumulate
            with self._free_cv:
                batch += self._free_queue
                self._free_queue = []
            by_node: Dict[str, list] = {}
            for node_hex, ob in batch:
                by_node.setdefault(node_hex, []).append(ob)
            for node_hex, obs in by_node.items():
                # nothing here may escape: one bad node/GCS hiccup must not
                # kill the only consumer of the free queue
                try:
                    addr = self._node_address(node_hex)
                    if addr is None:
                        continue
                    conn = self._owner_conn(addr)
                    conn.call("free_objects", {"object_ids": obs},
                              timeout=5.0)
                except Exception:
                    pass

    def _note_pin(self, oid: ObjectID, pin_out: Optional[list] = None
                  ) -> None:
        with self._pins_lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1
        if pin_out is not None:
            pin_out.append(1)

    def _release_pins(self, oid: ObjectID) -> None:
        with self._pins_lock:
            count = self._pins.pop(oid, 0)
        for _ in range(count):
            try:
                self.store.release(oid)
            except Exception:
                break

    def _release_pins_n(self, oid: ObjectID, n: int) -> None:
        """Release exactly the ``n`` pins the caller itself took — a
        concurrent fetch of the same object may hold live views under
        its own pins, so blanket _release_pins would be unsound here."""
        with self._pins_lock:
            count = self._pins.get(oid, 0)
            take = min(count, n)
            if take <= 0:
                return
            if count - take <= 0:
                self._pins.pop(oid, None)
            else:
                self._pins[oid] = count - take
        for _ in range(take):
            try:
                self.store.release(oid)
            except Exception:
                break

    def release_borrowed(self, oids) -> None:
        """Drop pins + cached values for borrowed objects (a worker calls
        this after finishing the task that resolved them)."""
        for oid in oids:
            with self._owned_lock:
                if oid in self._owned:
                    continue  # owned objects are managed by refcounting
                self._drop_cached(oid)
            self._release_pins(oid)

    # ------------------------------------------------------------- put/get
    def put(self, value: Any) -> ObjectRef:
        _t0 = rtm.now()
        with self._counter_lock:
            self._put_counter += 1
            idx = self._put_counter
        oid = ObjectID.for_put(self.current_task_id, idx)
        head, views = ser.serialize(value)
        size = ser.serialized_size(head, views)
        entry = _OwnedObject()
        entry.state = "ready"
        with self._owned_lock:
            self._owned[oid] = entry
        if size <= _INLINE_MAX:
            entry.data = ser.to_flat_bytes(head, views)
            self._memory_cache[oid] = value
        else:
            self.store_put(oid, head, views)
            entry.locations.add(self.node_id)
            entry.size = size
        entry.event.set()
        _M_PUT.observe_since(_t0)
        return ObjectRef(oid, self.address, self)

    def store_put(self, oid: ObjectID, head, views,
                   error: bool = False) -> None:
        """Write a primary copy into local shm.  Primaries are never
        LRU-evicted (allow_evict=False); on a full store the raylet spills
        LRU objects to disk and the create retries.  If spilling can't make
        room (everything is pinned by readers), the copy is born on disk
        instead of failing — the reference's plasma fallback allocation
        (object_store_fallback_dir)."""
        size = ser.serialized_size(head, views)
        for _ in range(3):
            try:
                self.store.put_serialized(oid, head, views, error=error,
                                          allow_evict=False)
                return
            except FileExistsError:
                return  # immutable: an identical reconstruction beat us
            except exc.ObjectStoreFullError:
                try:
                    reply = self._raylet.call(
                        "request_spill", {"bytes": size},
                        timeout=CONFIG.raylet_rpc_timeout_s)
                    freed = reply.get("freed", 0)
                except (ConnectionError, rpc.RpcError, TimeoutError,
                        OSError):
                    freed = 0
                if freed < size:
                    break  # nothing left to spill: fall back to disk
                time.sleep(0.01)
        self._put_fallback(oid, head, views, error)

    def _put_fallback(self, oid: ObjectID, head, views,
                      error: bool) -> None:
        """Write the primary copy straight into the raylet's spill dir
        (same host, shared filesystem) and register it; fetches stream or
        restore it like any spilled object."""
        import os
        try:
            spill_dir = self._raylet.call(
                "spill_dir", {}, timeout=CONFIG.raylet_rpc_timeout_s)
        except rpc.RemoteError as e:
            if "out of disk" in str(e):
                # shm full AND disk full: degrade with a clear error
                # instead of a hang (reference OutOfDiskError)
                raise exc.OutOfDiskError(str(e)) from None
            raise
        path = os.path.join(spill_dir, oid.hex())
        tmp = f"{path}.tmp{os.getpid()}"
        total = ser.serialized_size(head, views)
        buf = bytearray(total)
        ser.write_into(memoryview(buf), head, views)
        with open(tmp, "wb") as f:
            f.write(buf)
        os.replace(tmp, path)
        self._raylet.call("register_spilled",
                          {"object_id": oid.binary(), "size": total,
                           "meta": 1 if error else 0},
                          timeout=CONFIG.raylet_rpc_timeout_s)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None
            ) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        return [self._get_one(r, deadline) for r in refs]

    def _remaining(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.id
        if oid in self._memory_cache:
            return self._memory_cache[oid]
        _t0 = rtm.now()
        pins: list = []   # shm pins THIS fetch takes (see _note_pin)
        data = self._fetch_serialized(ref, deadline, pins)
        if data is None:
            raise exc.GetTimeoutError(f"get timed out on {ref}")
        try:
            # raises stored task errors
            value, holds_views = ser.deserialize_with_viewinfo(data)
        except BaseException:
            # no value materialized, so nothing can hold views: drop the
            # pins this fetch took or every get of a stored error / un-
            # importable payload leaks one pin per attempt
            if pins:
                data = None
                self._release_pins_n(oid, len(pins))
            raise
        if pins and not holds_views:
            # self-contained value (no zero-copy views into the
            # segment): drop our pins now instead of carrying them until
            # cache eviction — a consumer draining a long generator
            # stream must not pin every consumed item (the
            # object_store.py:293 leak)
            data = None
            self._release_pins_n(oid, len(pins))
        self._memory_cache[oid] = value
        with self._owned_lock:
            borrowed = oid not in self._owned
        if borrowed:
            tok = next(self._borrowed_seq)
            self._borrowed_tokens[oid] = tok
            self._borrowed_cache_order.append((oid, tok))
            self._maybe_trim_cache()
        _M_GET.observe_since(_t0)
        return value

    def _drop_cached(self, oid: ObjectID) -> None:
        """Remove a cached value AND its borrowed-FIFO claim; every path
        that pops _memory_cache for a possibly-borrowed oid must come
        through here or the FIFO entry goes stale."""
        self._memory_cache.pop(oid, None)
        self._borrowed_tokens.pop(oid, None)

    def _maybe_trim_cache(self, cap: int = 4096) -> None:
        """Bound the borrowed portion of the value cache (owned entries
        are evicted by refcounting; borrowed ones would otherwise
        accumulate in long-lived pooled workers).  O(1) amortized: only
        the borrowed-insertion FIFO is walked, never the whole cache."""
        while len(self._borrowed_cache_order) > cap:
            oid, tok = self._borrowed_cache_order.popleft()
            if self._borrowed_tokens.get(oid) != tok:
                continue  # superseded or released: not ours to evict
            self._borrowed_tokens.pop(oid, None)
            if self._memory_cache.pop(oid, None) is not None:
                self._release_pins(oid)

    def _fetch_serialized(self, ref: ObjectRef,
                          deadline: Optional[float],
                          pin_out: Optional[list] = None
                          ) -> Optional[memoryview]:
        oid = ref.id
        # 1. owned inline
        with self._owned_lock:
            entry = self._owned.get(oid)
        if entry is not None:
            evac_tried: set = set()
            while True:
                t = self._remaining(deadline)
                if not entry.event.wait(t if t is not None else None):
                    return None
                with self._owned_lock:
                    data = entry.data
                if data is not None:
                    return memoryview(data)
                # owned but stored in shm somewhere
                res = self._fetch_from_locations(oid, entry, deadline,
                                                 pin_out)
                if res is not None:
                    return res
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                # a drained node may have evacuated the copy before
                # dying: consult the GCS hint table before burning a
                # reconstruction (docs/fault_tolerance.md)
                if self._merge_evacuated_locations(oid, entry, evac_tried):
                    continue
                # every live copy is gone: recover via lineage or give up
                # (reference ObjectRecoveryManager::RecoverObject,
                # object_recovery_manager.h:41)
                if not self._try_reconstruct(oid, entry):
                    raise exc.ObjectLostError(
                        f"object {oid.hex()[:16]} lost: all copies are gone "
                        f"and it cannot be reconstructed (put objects and "
                        f"tasks out of retries/reconstruction budget are "
                        f"unrecoverable)"
                        + (f"; last copy died with node "
                           f"{entry.last_lost_node[:12]} — see "
                           f".debug_dossier()" if entry.last_lost_node
                           else ""),
                        dossier_id=entry.last_lost_node)
        # 2. local shm (argument prefetch lands borrowed copies here:
        # the hit counter is the numerator of the prefetch hit ratio)
        res = self.store.get(oid, timeout=0.0)
        if res is not None:
            buf, _ = res
            _M_FETCH_LOCAL.inc()
            self._note_pin(oid, pin_out)
            return buf
        # 3. ask the owner
        return self._fetch_from_owner(ref, deadline, pin_out)

    def _alive_node_ids(self, max_age: float = 1.0) -> set:
        """Node liveness view, refreshed from the GCS at most every
        ``max_age`` seconds.  Empty set means 'unknown' (GCS unreachable
        before the first successful refresh) — callers must not prune on
        an empty view."""
        ts, cached = self._alive_cache
        now = time.monotonic()
        if now - ts <= max_age:
            return cached
        try:
            nodes = self.gcs.call("list_nodes", timeout=5)
        except (ConnectionError, rpc.RpcError, TimeoutError, OSError):
            return cached
        for n in nodes:
            self._node_table[n["node_id"]] = n
        cached = {n["node_id"] for n in nodes if n["alive"]}
        self._alive_cache = (now, cached)
        return cached

    def _prune_dead_locations(self, entry: _OwnedObject) -> set:
        """Drop locations on dead nodes from an owned entry; a dead node's
        copy never comes back (its shm segment died with it)."""
        alive = self._alive_node_ids()
        with self._owned_lock:
            if alive:
                lost = entry.locations - alive
                if lost:
                    # remember who lost the (so far) last copy: if
                    # lineage is later exhausted, ObjectLostError names
                    # this node's dossier
                    entry.last_lost_node = sorted(lost)[0]
                entry.locations &= alive
            return set(entry.locations)

    def _fetch_from_locations(self, oid: ObjectID, entry: _OwnedObject,
                              deadline: Optional[float],
                              pin_out: Optional[list] = None
                              ) -> Optional[memoryview]:
        """Owner-side fetch of an owned shm object: local shm first, then
        one striped pull across every live location at once (including our
        own raylet, which may hold it as a spill file).  Returns None only
        once the object is genuinely unavailable — every location is dead,
        or definitively reports the copy gone, or has been unreachable
        past fetch_fail_timeout_s — so the caller can decide between
        reconstruction and timeout.  A raylet that *answers* "absent"
        drops that location immediately; a raylet that can't be reached
        gets the grace window (its node may just be restarting) instead of
        triggering a duplicate re-execution."""
        grace = time.monotonic() + CONFIG.fetch_fail_timeout_s
        attempt = 0
        while True:
            locations = self._prune_dead_locations(entry)
            if not locations:
                return None
            if self.node_id in locations:
                res = self.store.get(oid, timeout=0.0)
                if res is not None:
                    self._note_pin(oid, pin_out)
                    return res[0]
            out = self._puller.pull(oid, sorted(locations), deadline)
            if out.absent:
                # evicted/never there: those locations are authoritative
                # about themselves — forget them
                with self._owned_lock:
                    entry.locations -= out.absent
            if out.status == "ok":
                self._finish_pull(oid, out, pin_out)
                if out.published:
                    with self._owned_lock:
                        entry.locations.add(self.node_id)
                return out.data if out.published else memoryview(out.data)
            if not out.transient:
                return None  # every remaining location answered "absent"
            now = time.monotonic()
            if now >= grace or (deadline is not None and now >= deadline):
                return None
            attempt += 1
            time.sleep(min(0.05 * attempt, 1.0))

    def _fetch_from_location_set(self, ref: "ObjectRef", locations: set,
                                 deadline: Optional[float],
                                 pin_out: Optional[list] = None
                                 ) -> Optional[memoryview]:
        """Borrower-side striped pull over owner-reported locations."""
        oid = ref.id
        alive = self._alive_node_ids()
        if self.node_id in locations:
            res = self.store.get(oid, timeout=0.0)
            if res is not None:
                self._note_pin(oid, pin_out)
                return res[0]
        # self stays in the source set: our own raylet may hold the copy
        # as a spill file (the engine's pull restores or streams it)
        sources = [nh for nh in sorted(locations)
                   if not alive or nh in alive]
        if not sources:
            return None
        out = self._puller.pull(oid, sources, deadline)
        if out.status != "ok":
            return None
        self._finish_pull(oid, out, pin_out)
        if out.published:
            # tell the owner this node now holds a copy: later pulls can
            # stripe across us, and the final free sweeps our copy too
            self._report_location(ref, out.bytes)
            return out.data
        return memoryview(out.data)

    def _finish_pull(self, oid: ObjectID, out, pin_out) -> None:
        """Shared bookkeeping for a successful remote pull."""
        _M_FETCH_REMOTE.inc()
        if out.published:
            # the engine holds the single store pin for the sealed copy;
            # account it like any local-shm pin this fetch took
            self._note_pin(oid, pin_out)
        if out.bytes >= CONFIG.object_transfer_chunk_bytes \
                and not oid.is_put():
            # put objects have a pseudo task id with no task record:
            # recording against it would fabricate a phantom stub row in
            # the GCS task table / `ray-tpu status`
            # timeline slice per multi-chunk pull (docs/observability.md):
            # rides the producing task's event record
            # no name: the task record keeps the producing task's name
            # the event rides the producing task's record, but the slice
            # belongs to THIS process's row — stamp the puller's ids
            self.events.record(
                oid.task_id().hex(), "PULL",
                dur_ms=round(out.duration_s * 1000.0, 3),
                bytes=out.bytes, nsources=out.nsources,
                object_id=oid.hex()[:16],
                node_id=self.node_id,
                worker_id=self.worker_id.hex())

    def _report_location(self, ref: "ObjectRef", size: int) -> None:
        """Fire-and-forget location update to the owner after a pulled
        copy was published into local shm (the ownership directory's
        OnObjectLocationAdded analog): grows the owner's location set so
        later pulls can stripe across this node."""
        try:
            conn = self._owner_conn(tuple(ref.owner_addr))
            conn.call_async("report_object_location",
                            {"object_id": ref.id.binary(),
                             "node_id": self.node_id, "size": size})
        except Exception:
            pass  # purely an optimization; the owner survives without it

    def _node_address(self, node_hex: str) -> Optional[Tuple[str, int]]:
        node = self._node_table.get(node_hex)
        if node is None:
            for n in self.gcs.call("list_nodes"):
                self._node_table[n["node_id"]] = n
            node = self._node_table.get(node_hex)
        return tuple(node["address"]) if node else None

    def _owner_conn(self, addr: Tuple[str, int]) -> rpc.Connection:
        return self._owner_conns.get(tuple(addr))

    def _fetch_from_owner(self, ref: ObjectRef,
                          deadline: Optional[float],
                          pin_out: Optional[list] = None
                          ) -> Optional[memoryview]:
        while True:
            t = self._remaining(deadline)
            try:
                conn = self._owner_conn(ref.owner_addr)
                res = conn.call("get_object", {
                    "object_id": ref.id.binary(),
                    "timeout": min(t, 2.0) if t is not None else 2.0,
                }, timeout=CONFIG.gcs_rpc_timeout_s)
            except (ConnectionError, rpc.RemoteError, OSError):
                data = self._orphan_borrower_fetch(ref, deadline, pin_out)
                if data is not None:
                    return data
                raise exc.ObjectLostError(
                    f"owner of {ref} unreachable at {ref.owner_addr} and "
                    f"no surviving copy found (evacuation hints + live-"
                    f"node sweep)")
            if res is not None:
                if "data" in res:
                    return memoryview(res["data"])
                # location answer
                data = self._fetch_from_location_set(
                    ref, set(res["locations"]), deadline, pin_out)
                if data is not None:
                    return data
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.01)

    def _orphan_borrower_fetch(self, ref: ObjectRef,
                               deadline: Optional[float],
                               pin_out: Optional[list] = None
                               ) -> Optional[memoryview]:
        """Owner-death fallback for borrowed refs (docs/fault_tolerance.md):
        the bytes may well outlive the owner — a drained node evacuated
        its primaries into survivors (GCS hint table), or the copy sits
        in a surviving node's store while only the owning *process* died
        (sharded train checkpoints put by gang workers outlive the gang
        teardown exactly this way).  Consult the hint table, then sweep
        the live nodes with one striped pull; raylets that answer
        "absent" drop out of the source set inside the engine."""
        oid = ref.id
        nodes: set = set()
        try:
            hints = self.gcs.call("get_evacuated_locations",
                                  {"object_ids": [oid.hex()]}, timeout=5)
            nodes |= set((hints or {}).get(oid.hex(), ()))
        except (ConnectionError, rpc.RpcError, TimeoutError, OSError):
            pass
        nodes |= self._alive_node_ids()
        nodes.discard(self.node_id)   # local shm was already tried
        if not nodes:
            return None
        return self._fetch_from_location_set(ref, nodes, deadline, pin_out)

    def _merge_evacuated_locations(self, oid: ObjectID,
                                   entry: _OwnedObject,
                                   tried: set) -> bool:
        """Grow the entry's location set from the GCS evacuated-object
        table (docs/fault_tolerance.md): a draining node ships its
        primary copies to survivors and registers each landing, so an
        owner whose old locations died finds the copy here instead of
        re-executing lineage.  ``tried`` keeps one fetch attempt from
        looping on a hint whose copy turned out absent.  Returns True
        when a new candidate location was merged."""
        try:
            hints = self.gcs.call("get_evacuated_locations",
                                  {"object_ids": [oid.hex()]}, timeout=5)
        except (ConnectionError, rpc.RpcError, TimeoutError, OSError):
            return False
        nodes = set((hints or {}).get(oid.hex(), ())) - tried
        if not nodes:
            return False
        alive = self._alive_node_ids()
        if alive:
            # liveness-filter BEFORE marking tried: a hint whose target
            # isn't in the (≤1s-stale) alive view yet must stay
            # consultable on the next attempt, not be consumed unseen
            nodes &= alive
        if not nodes:
            return False
        tried |= nodes
        with self._owned_lock:
            entry.locations |= nodes
        logger.info("object %s: following evacuated copy to %s",
                    oid.hex()[:12], sorted(n[:8] for n in nodes))
        return True

    # ------------------------------------------------------- reconstruction
    def _try_reconstruct(self, oid: ObjectID, entry: _OwnedObject) -> bool:
        """All copies of an owned object are gone: resubmit the task that
        produced it from its pinned spec (reference
        TaskManager::ResubmitTask, task_manager.h:146).  Returns True if a
        recovery is in flight (the entry's event will be set again);
        idempotent — concurrent callers piggyback on one resubmission."""
        with self._owned_lock:
            if entry.state == "pending":
                return True  # recovery already in flight
            meta = entry.task_spec
            if meta is None:
                return False
            if meta["retries_left"] <= 0:
                return False
            if entry.recon_attempts >= \
                    CONFIG.object_reconstruct_max_attempts:
                # per-object budget on top of task retries: a flapping
                # node repeatedly losing the same object converges to
                # ObjectLostError instead of resubmitting forever
                logger.warning(
                    "object %s: reconstruction budget exhausted "
                    "(%d attempts)", oid.hex()[:12], entry.recon_attempts)
                return False
            entry.recon_attempts += 1
            meta["retries_left"] -= 1  # shared dict: visible to all slots
            spec = meta["spec"]
            task_id = TaskID(spec["task_id"])
            lmeta = self._lineage_meta.get(task_id.binary())
            # reset every return slot of the task (the resubmission
            # regenerates them all), including adopted dynamic children
            slots = {ObjectID.for_task_return(task_id, i)
                     for i in range(num_return_slots(spec["num_returns"]))}
            if lmeta is not None:
                slots |= lmeta["slots"]
            for sib_oid in slots:
                sib = self._owned.get(sib_oid)
                if sib is None:
                    continue
                sib.task_spec = meta
                sib.state = "pending"
                sib.data = None
                sib.error = 0
                sib.locations.clear()
                sib.event.clear()
                self._memory_cache.pop(sib_oid, None)
        logger.info("reconstructing object %s: resubmitting task %s "
                    "(%d retries left)", oid.hex()[:12],
                    spec.get("name", ""), meta["retries_left"])
        self.events.record(task_id.hex(), "RECONSTRUCTING",
                           name=spec.get("name", ""))
        self._enqueue_task(meta["key"], meta["resources"], spec,
                           meta["retries_left"],
                           strategy=meta.get("strategy"),
                           env=meta.get("env"))
        return True

    def _recover_or_fail(self, oid: ObjectID, entry: _OwnedObject) -> None:
        """Owner-side recovery entry point for borrower-driven gets: either
        kick off reconstruction or resolve the entry to ObjectLostError so
        every waiter (local and remote) gets a clean failure."""
        try:
            # an evacuated copy beats re-execution: merge any hint the
            # draining node registered before reconstructing.  The
            # tried set persists on the entry — borrowers poll every
            # 10 ms, and a stale hint must be followed once, not
            # re-merged on every recovery attempt
            with self._owned_lock:
                if entry.evac_tried is None:
                    entry.evac_tried = set()
                tried = entry.evac_tried
            if self._merge_evacuated_locations(oid, entry, tried):
                return
            if self._try_reconstruct(oid, entry):
                return
            err = exc.ObjectLostError(
                f"object {oid.hex()[:16]} lost: all copies are gone and it "
                f"cannot be reconstructed",
                dossier_id=entry.last_lost_node)
            head, views = ser.serialize(err, error_type=ser.ERROR_OBJECT_LOST)
            data = ser.to_flat_bytes(head, views)
            with self._owned_lock:
                if entry.state == "ready" and entry.data is None \
                        and not entry.locations:
                    entry.data = data
                    entry.error = ser.ERROR_OBJECT_LOST
                    entry.event.set()
        finally:
            with self._owned_lock:
                entry.recovering = False

    def result_is_error(self, ref: ObjectRef) -> bool:
        """Whether a READY owned ref resolved to an error payload —
        without deserializing (the serve trace roots classify a
        completed request's status off the reply the moment its ready
        callback fires)."""
        with self._owned_lock:
            entry = self._owned.get(ref.id)
            return bool(entry is not None and entry.error)

    def add_ready_callback(self, ref: ObjectRef, cb) -> None:
        """Run ``cb()`` once the owned object is ready — immediately when
        it already is (or when the ref isn't owned by this worker, where
        readiness can't be observed locally; callers use this for refs
        they own, e.g. Serve handles watching their replica calls)."""
        with self._owned_lock:
            entry = self._owned.get(ref.id)
        if entry is None or not entry.event.add_callback(cb):
            cb()

    # ------------------------------------------------------------- wait
    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        refs = list(refs)
        if len({r.id for r in refs}) != len(refs):
            # reference parity (worker.py wait): duplicates would also make
            # num_returns unsatisfiable and spin forever
            raise ValueError("wait() requires a list of unique object refs")
        ready: List[ObjectRef] = []
        while True:
            ready = [r for r in refs if self._is_ready(r)]
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.002)
        ready_set = {r.id for r in ready[:num_returns]}
        ready_list = [r for r in refs if r.id in ready_set]
        rest = [r for r in refs if r.id not in ready_set]
        return ready_list, rest

    def _is_ready(self, ref: ObjectRef) -> bool:
        if ref.id in self._memory_cache:
            return True
        with self._owned_lock:
            entry = self._owned.get(ref.id)
        if entry is not None:
            return entry.event.is_set()
        if self.store.contains(ref.id):
            return True
        # borrowed & remote: ask owner without blocking
        try:
            conn = self._owner_conn(ref.owner_addr)
            res = conn.call("get_object", {"object_id": ref.id.binary(),
                                           "timeout": 0.0,
                                           "probe": True}, timeout=5.0)
            return res is not None
        except (ConnectionError, rpc.RemoteError, TimeoutError, OSError):
            return False

    # -------------------------------------------------- function registry
    def register_function(self, func) -> str:
        # hot path: every task submission lands here, and cloudpickling the
        # function just to recompute its content hash dominates small-task
        # submit cost.  The id() cache pins each cached function object
        # explicitly — without the pin, a duplicate-hash function could be
        # collected and its id recycled by a different function, which
        # would then silently run the wrong code remotely.
        cached = self._fn_key_by_id.get(id(func))
        if cached is not None:
            return cached
        blob = cloudpickle.dumps(func)
        key = hashlib.sha1(blob).hexdigest()
        full = f"fn:{self.job_id.hex()}:{key}"
        if full not in self._fn_cache:
            self.gcs.kv_put(full, blob, overwrite=False)
            self._fn_cache[full] = func
        # bound the local caches: drivers that build a fresh closure per
        # submission would otherwise pin every one (and whatever arrays it
        # captured) forever.  Dropping them just costs cache hits — the
        # blobs stay exported in GCS KV for the job's lifetime, like the
        # reference's per-job function table.
        if len(self._fn_key_by_id) >= 4096:
            self._fn_key_by_id.clear()
            self._fn_id_pins.clear()
        if len(self._fn_cache) >= 4096:
            self._fn_cache.clear()
        self._fn_key_by_id[id(func)] = full
        self._fn_id_pins[id(func)] = func
        return full

    def load_function(self, key: str):
        fn = self._fn_cache.get(key)
        if fn is None:
            blob = self.gcs.kv_get(key)
            if blob is None:
                raise exc.RayTpuError(f"function {key} not found in GCS")
            fn = cloudpickle.loads(blob)
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------ task submission
    def submit_task(self, func, args: tuple, kwargs: dict, *,
                    num_returns=1,
                    resources: Optional[Dict[str, float]] = None,
                    max_retries: int = 3,
                    name: str = "",
                    scheduling_key: Optional[str] = None,
                    scheduling_strategy: Optional[dict] = None,
                    runtime_env: Optional[dict] = None,
                    fn_key: Optional[str] = None,
                    language: Optional[str] = None
                    ) -> List[ObjectRef]:
        # cross-language tasks carry a pre-resolved key ("cpp:Name") the
        # target-language worker resolves in its own registry (reference
        # cross_language.py — no function export through the GCS)
        if fn_key is None:
            fn_key = self.register_function(func)
        task_id = TaskID.from_random()
        resources = dict(resources or {})
        runtime_env = runtime_env or self.job_runtime_env
        # scheduling key = resource footprint (not the function): workers are
        # fungible across functions, so leases and the raylet's idle pool are
        # shared by everything with the same shape (cf. reference
        # SchedulingKey in direct_task_transport.h — runtime_env + resources)
        key = scheduling_key or (
            self.job_id.hex()[:8] + "|" +
            ",".join(f"{k}={v}" for k, v in sorted(resources.items())))
        if scheduling_strategy:
            key += "|" + ",".join(
                f"{k}={scheduling_strategy[k]}"
                for k in sorted(scheduling_strategy))
        if runtime_env:
            # workers are env-specific: a different runtime_env must never
            # reuse another env's idle workers (reference SchedulingKey
            # includes the serialized runtime env)
            key += "|env=" + runtime_env["hash"]
        if language:
            # a cpp lease must never reuse (or be reused by) python
            # workers from the same resource-shaped pool
            key += f"|lang={language}"
        arg_blob, live_refs = self._serialize_args(args, kwargs)
        if live_refs:
            self._arg_refs[task_id.binary()] = live_refs
        spec = {
            "task_id": task_id.binary(),
            "fn_key": fn_key,
            "args": arg_blob,
            "num_returns": num_returns,
            "owner_addr": list(self.address),
            "name": name or getattr(func, "__name__", "task"),
        }
        if live_refs:
            # ObjectRef-carrying specs never share a push_tasks frame —
            # see _drain_batch_locked
            spec["_refs"] = True
        if num_returns == "streaming":
            # the owner's config governs the stream it consumes; the
            # worker honors the stamped window, so no env propagation of
            # the flag is needed
            spec["backpressure"] = CONFIG.generator_backpressure_num_objects
            self._register_stream(task_id.binary(), spec["backpressure"])
        trace_ctx = _submit_trace_ctx(spec["name"])
        if trace_ctx:
            # auto span injection (reference _inject_tracing_into_function,
            # tracing_helper.py:324): the submitting span's context rides
            # the spec so worker-side events/spans join the same trace
            spec["trace_ctx"] = trace_ctx
        return_refs = []
        n_slots = num_return_slots(num_returns)
        # lineage stays an in-process dict (never crosses a wire); pickling
        # it per submission doubled small-task submit cost for no benefit.
        # The spec is never mutated after submission (workers get an RPC
        # copy), so sharing one dict across sibling slots is safe; the
        # byte ledger uses the dominant term (args) plus flat overhead.
        lineage = {"spec": spec, "resources": resources, "key": key,
                   "retries_left": max_retries,
                   "strategy": scheduling_strategy, "env": runtime_env}
        lineage_size = len(arg_blob) + 512
        with self._owned_lock:
            slots = set()
            for i in range(n_slots):
                oid = ObjectID.for_task_return(task_id, i)
                entry = _OwnedObject()
                entry.task_spec = lineage
                self._owned[oid] = entry
                slots.add(oid)
                return_refs.append(ObjectRef(oid, self.address, self))
            self._lineage_meta[task_id.binary()] = {
                "size": lineage_size, "slots": slots, "evicted": False}
            self._lineage_order.append(task_id.binary())
            self._lineage_bytes += lineage_size
            self._evict_lineage_locked()
        if _TELEMETRY:
            self._task_t0[task_id.binary()] = rtm.now()
            self._task_tq[task_id.binary()] = self._task_t0[
                task_id.binary()]
        self._enqueue_task(key, resources, spec, max_retries,
                           strategy=scheduling_strategy, env=runtime_env,
                           language=language)
        self.events.record(task_id.hex(), "SUBMITTED", name=spec["name"])
        return return_refs

    def _evict_lineage_locked(self) -> None:
        """owned_lock held: enforce lineage_max_bytes FIFO — evicted tasks'
        objects become unrecoverable (their specs and pinned arg refs are
        dropped), matching the reference's lineage eviction
        (task_manager lineage footprint accounting)."""
        budget = CONFIG.lineage_max_bytes
        # small rotation cap: this runs under _owned_lock on every task
        # submission, so the scan must stay O(1) per call — rotation makes
        # successive calls examine different entries, so progress past a
        # pending head accumulates across submissions instead
        rotations = min(16, len(self._lineage_order))
        while self._lineage_bytes > budget and self._lineage_order:
            tb = self._lineage_order[0]
            meta = self._lineage_meta.get(tb)
            if meta is None or meta["evicted"]:
                self._lineage_order.popleft()
                continue
            # never evict lineage of a task whose outputs are still pending
            # (its spec is also the retry path for worker death) — but
            # rotate past it rather than stopping, so one long-running head
            # task can't pin every completed task behind it over budget
            if any(self._owned[o].state == "pending"
                   for o in meta["slots"] if o in self._owned):
                if rotations <= 0:
                    break
                rotations -= 1
                self._lineage_order.rotate(-1)
                continue
            self._lineage_order.popleft()
            meta["evicted"] = True
            self._lineage_bytes -= meta["size"]
            for o in meta["slots"]:
                e = self._owned.get(o)
                if e is not None:
                    e.task_spec = None
            self._arg_refs.pop(tb, None)

    def _serialize_args(self, args: tuple, kwargs: dict):
        """Pickle args; ObjectRefs become markers resolved executor-side.
        Large plain values are auto-promoted to the store first (cf.
        reference max_direct_call_object_size).  Returns (blob, live_refs):
        the caller must keep ``live_refs`` alive until the task completes so
        the owner doesn't free argument objects mid-flight."""
        promoted_args = []
        live_refs = []
        for a in args:
            if not isinstance(a, ObjectRef):
                blob_size = len(cloudpickle.dumps(a, protocol=5)) \
                    if _maybe_big(a) else 0
                if blob_size > CONFIG.max_direct_call_args_bytes:
                    a = self.put(a)
            if isinstance(a, ObjectRef):
                live_refs.append(a)
            promoted_args.append(a)
        for v in kwargs.values():
            if isinstance(v, ObjectRef):
                live_refs.append(v)
        return cloudpickle.dumps((tuple(promoted_args), kwargs)), live_refs

    def _serialize_args_tracked(self, args, kwargs, task_id: TaskID) -> bytes:
        blob, live_refs = self._serialize_args(args, kwargs)
        if live_refs:
            self._arg_refs[task_id.binary()] = live_refs
        return blob

    def _store_task_error(self, spec, error: BaseException,
                          error_code: int = ser.ERROR_TASK) -> None:
        task_id = TaskID(spec["task_id"])
        self._arg_refs.pop(spec["task_id"], None)
        self._oom_retries.pop(spec["task_id"], None)
        t0 = self._task_t0.pop(spec["task_id"], None)
        self._task_tq.pop(spec["task_id"], None)
        if t0 is not None:
            _M_TASK_E2E.observe_since(t0)
        self.events.record(task_id.hex(), "FAILED", name=spec.get("name", ""),
                           error_type=type(error).__name__)
        head, views = ser.serialize(error, error_type=error_code)
        data = ser.to_flat_bytes(head, views)
        freed: List[Tuple[ObjectID, set]] = []
        with self._owned_lock:
            for i in range(num_return_slots(spec["num_returns"])):
                oid = ObjectID.for_task_return(task_id, i)
                entry = self._owned.get(oid)
                if entry is not None:
                    entry.data = data
                    entry.state = "ready"
                    entry.error = error_code
                    entry.event.set()
                    if entry.refcount <= 0:
                        self._free_entry_locked(oid, entry, freed)
        self._complete_frees(freed)
        if spec.get("num_returns") == "streaming":
            self._stream_finished(spec["task_id"], failed=True)

    # ----- per-key scheduling queue: leased workers pull pending specs -----
    def _sched_state(self, key: str, resources,
                     strategy: Optional[dict] = None,
                     env: Optional[dict] = None,
                     language: Optional[str] = None) -> Dict[str, Any]:
        with self._sched_lock:
            st = self._sched.get(key)
            if st is None:
                st = {"queue": deque(), "leases": [], "requesting": False,
                      "idle": 0,  # leases parked in keepalive
                      "resources": dict(resources), "strategy": strategy,
                      "env": env, "language": language}
                self._sched[key] = st
            return st

    def _enqueue_task(self, key, resources, spec, retries: int,
                      strategy: Optional[dict] = None,
                      env: Optional[dict] = None,
                      language: Optional[str] = None) -> None:
        st = self._sched_state(key, resources, strategy, env, language)
        with self._sched_lock:
            st["queue"].append((spec, retries))
            self._sched_cv.notify_all()
        self._maybe_request_lease(key, st)

    def _maybe_request_lease(self, key: str, st) -> None:
        with self._sched_lock:
            if (st["requesting"] or not st["queue"]
                    or self._shutdown.is_set()
                    or 0 < len(st["queue"]) <= st.get("idle", 0)):
                # the last clause: idle keepalive leases were just
                # notified and can absorb this little work by themselves
                # (if one instead times out, it decrements "idle" and
                # re-checks the queue under this same lock before
                # exiting, so the task cannot be stranded).  A burst
                # deeper than the parked capacity still requests leases —
                # keepalive must not collapse fan-out for parallel
                # workloads.
                return
            st["requesting"] = True
        threading.Thread(target=self._lease_request_loop, args=(key, st),
                         daemon=True).start()

    def _lease_request_loop(self, key: str, st) -> None:
        """At most one in-flight lease request per scheduling key."""
        try:
            while True:
                with self._sched_lock:
                    if not st["queue"] or self._shutdown.is_set():
                        return
                try:
                    grant = self._lease_with_spillback(key, st)
                    # the worker streams per-task task_done pushes over the
                    # lease connection (early results for mid-frame specs);
                    # the box defers binding until the lease exists
                    lease_box: list = []

                    def _on_push(method, payload, _box=lease_box):
                        if method == "task_done" and _box:
                            self._lease_task_done(_box[0], payload)

                    conn = rpc.connect(tuple(grant["address"]),
                                       push_handler=_on_push)
                except SchedulingError as e:
                    # permanent strategy failure (pg removed, bad bundle
                    # index, hard affinity to a dead node): fail the queued
                    # tasks instead of respawning the loop forever
                    self._fail_queued(st, exc.RayTpuError(str(e)))
                    return
                except (ConnectionError, rpc.RpcError, TimeoutError) as e:
                    # resources busy / raylet hiccup: if existing leases are
                    # draining the queue that's fine; otherwise keep trying
                    with self._sched_lock:
                        have_workers = bool(st["leases"])
                        pending = bool(st["queue"])
                    if not pending:
                        return
                    if not have_workers and self._shutdown.is_set():
                        return
                    if not have_workers and isinstance(e, ConnectionError):
                        self._fail_queued(st, exc.RayTpuError(
                            f"raylet unreachable: {e}"))
                        return
                    time.sleep(0.2)
                    continue
                lease = _Lease(key, grant, conn)
                lease_box.append(lease)
                with self._sched_lock:
                    st["leases"].append(lease)
                threading.Thread(target=self._lease_worker_loop,
                                 args=(key, st, lease), daemon=True).start()
        finally:
            with self._sched_lock:
                st["requesting"] = False
            # new tasks may have arrived while we were exiting
            with self._sched_lock:
                need_more = bool(st["queue"]) and not st["leases"]
            if need_more:
                self._maybe_request_lease(key, st)

    def _arg_hints(self, st) -> dict:
        """Argument locations/sizes of the queued tasks this lease will
        serve (head of the key's queue), for locality-aware placement and
        raylet-side prefetch (docs/object_transfer.md).  Only owned,
        ready, shm-resident arguments at least locality_min_arg_bytes
        participate — below that, transfer cost is noise next to lease
        latency, and pending/inline/borrowed arguments have no location
        worth weighing."""
        if not (CONFIG.locality_aware_scheduling
                or CONFIG.object_prefetch_enabled):
            return {}
        with self._sched_lock:
            specs = [spec for spec, _r in itertools.islice(
                st["queue"], 0, 4)]
        locs: Dict[str, float] = {}
        prefetch: List[dict] = []
        seen: set = set()
        for spec in specs:
            for ref in self._arg_refs.get(spec["task_id"], ()):
                if ref.id.binary() in seen:
                    continue
                seen.add(ref.id.binary())
                with self._owned_lock:
                    entry = self._owned.get(ref.id)
                    if (entry is None or entry.state != "ready"
                            or entry.data is not None
                            or entry.size < CONFIG.locality_min_arg_bytes
                            or not entry.locations):
                        continue
                    size = entry.size
                    locations = sorted(entry.locations)
                for nh in locations:
                    locs[nh] = locs.get(nh, 0.0) + size
                prefetch.append({"object_id": ref.id.binary(),
                                 "size": size, "locations": locations,
                                 "owner": list(self.address)})
        if not prefetch:
            return {}
        return {"arg_locs": locs, "prefetch": prefetch}

    def _lease_with_spillback(self, key: str, st) -> dict:
        """Lease locally; follow at most two retry_at redirects (the
        reference's spillback, direct_task_transport.cc retry_at_raylet).
        The grant remembers which raylet granted it so return_worker goes to
        the right node.  A scheduling strategy pins/redirects the lease
        before the default local-first path runs."""
        strategy = st.get("strategy")
        if strategy:
            grant = self._lease_with_strategy(key, st, strategy)
            if grant is not None:
                return grant
            # soft affinity fall-through: default path below
        payload = {"key": key, "resources": st["resources"],
                   "job_id": self.job_id.hex(), "env": st.get("env"),
                   "language": st.get("language")}
        payload.update(self._arg_hints(st))
        target_addr = None  # None -> local raylet
        for hop in range(3):
            if target_addr is None:
                grant = self._raylet.call(
                    "lease_worker", dict(payload, spillback=hop),
                    timeout=CONFIG.worker_lease_timeout_s + 5)
            else:
                conn = rpc.connect(target_addr)
                try:
                    grant = conn.call(
                        "lease_worker", dict(payload, spillback=hop),
                        timeout=CONFIG.worker_lease_timeout_s + 5)
                finally:
                    conn.close()
            if "retry_at" in grant:
                target_addr = tuple(grant["retry_at"])
                continue
            grant["granting_addr"] = target_addr  # None == local
            return grant
        raise rpc.RpcError("spillback loop exceeded")

    def _lease_at(self, addr: Optional[Tuple[str, int]],
                  payload: dict) -> dict:
        """One lease RPC to a specific raylet (no redirects honored)."""
        if addr is None:
            grant = self._raylet.call(
                "lease_worker", payload,
                timeout=CONFIG.worker_lease_timeout_s + 5)
        else:
            conn = rpc.connect(addr)
            try:
                grant = conn.call("lease_worker", payload,
                                  timeout=CONFIG.worker_lease_timeout_s + 5)
            finally:
                conn.close()
        grant["granting_addr"] = None if addr is None else list(addr)
        return grant

    def _lease_with_strategy(self, key: str, st,
                             strategy: dict) -> Optional[dict]:
        """Resolve a scheduling strategy to a pinned lease.

        placement_group -> lease from the bundle's reserved pool on its
        node; node_affinity -> lease from that raylet (soft falls back by
        returning None); spread -> least-loaded feasible node."""
        base = {"key": key, "resources": st["resources"],
                "job_id": self.job_id.hex(), "spillback": 2,
                "env": st.get("env"), "language": st.get("language")}
        # spillback=2 means the strategy's node choice is final — no
        # locality redirect — but the chosen raylet still prefetches
        hints = self._arg_hints(st)
        if hints.get("prefetch"):
            base["prefetch"] = hints["prefetch"]
        kind = strategy.get("type")
        if kind == "placement_group":
            pg_id = strategy["pg_id"]
            idx = int(strategy.get("bundle_index", -1))
            deadline = time.monotonic() + CONFIG.worker_lease_timeout_s
            while True:
                info = self.gcs.call("get_placement_group",
                                     {"pg_id": pg_id}, timeout=10)
                if info is None:
                    raise SchedulingError(
                        f"placement group {pg_id[:8]} removed")
                if info["state"] == "CREATED":
                    break
                if time.monotonic() > deadline:
                    raise rpc.RpcError(
                        f"placement group {pg_id[:8]} not placed in time")
                time.sleep(0.05)
            placement = info["placement"]
            if idx >= len(placement) or idx < -1:
                raise SchedulingError(
                    f"bundle index {idx} out of range for a "
                    f"{len(placement)}-bundle placement group")
            indices = [idx] if idx >= 0 else list(range(len(placement)))
            last_err: Optional[Exception] = None
            for i in indices:
                addr = self._node_address(placement[i])
                if addr is None:
                    continue
                try:
                    return self._lease_at(
                        addr, dict(base, bundle=[pg_id, i]))
                except (rpc.RemoteError, ConnectionError,
                        TimeoutError) as e:
                    last_err = e
            raise rpc.RpcError(
                f"no bundle of pg {pg_id[:8]} could grant a lease: "
                f"{last_err}")
        if kind == "node_affinity":
            addr = self._node_address(strategy["node_id"])
            if addr is None:
                if strategy.get("soft"):
                    return None
                raise SchedulingError(
                    f"node {strategy['node_id'][:8]} not found/alive")
            try:
                return self._lease_at(addr, dict(base))
            except (rpc.RemoteError, ConnectionError, TimeoutError) as e:
                if strategy.get("soft"):
                    return None
                raise rpc.RpcError(
                    f"node affinity lease failed: {e}") from e
        if kind == "spread":
            # pick the alive feasible node with the most available CPU,
            # breaking ties away from the most recently used one
            try:
                nodes = self.gcs.call("list_nodes", timeout=5)
            except (ConnectionError, rpc.RemoteError, TimeoutError):
                return None
            need = dict(st["resources"])
            need.setdefault("CPU", 1.0)
            feasible = [
                n for n in nodes if n["alive"] and
                all(n["available"].get(r, 0) >= v for r, v in need.items())]
            if not feasible:
                return None
            last = st.get("last_spread_node")
            feasible.sort(key=lambda n: (n["node_id"] == last,
                                         -n["available"].get("CPU", 0)))
            for n in feasible:
                addr = tuple(n["address"])
                try:
                    grant = self._lease_at(addr, dict(base))
                    st["last_spread_node"] = n["node_id"]
                    return grant
                except (rpc.RemoteError, ConnectionError, TimeoutError):
                    continue
            return None
        raise SchedulingError(f"unknown scheduling strategy {kind!r}")

    def _fail_queued(self, st, error: BaseException) -> None:
        with self._sched_lock:
            items = list(st["queue"])
            st["queue"].clear()
        for spec, _ in items:
            self._store_task_error(spec, error)

    # task specs in flight per lease connection: overlaps push RTT + spec
    # serialization with worker execution (the worker drains its own FIFO
    # serially, so this changes delivery, not execution concurrency) —
    # reference push-queue pipelining, direct_task_transport.cc:174/213
    _PUSH_WINDOW = 8

    def _drain_batch_locked(self, st, budget: int, batch_max: int) -> list:
        """_sched_lock held: pop up to min(budget, batch_max) specs for
        one push_tasks frame.  A spec with ObjectRef args always travels
        alone: the worker resolves its dependencies before enqueueing it,
        and a batch is only acked once every member has been enqueued —
        so a dependent batched behind its in-frame producer would wait on
        an ack that waits on it (head-of-line deadlock)."""
        batch = []
        limit = min(budget, batch_max)
        while (st["queue"] and not self._shutdown.is_set()
               and len(batch) < limit):
            spec, retries = st["queue"][0]
            if spec.get("_refs") and batch:
                break
            st["queue"].popleft()
            batch.append((spec, retries))
            if spec.get("_refs"):
                break
        return batch

    def _lease_worker_loop(self, key: str, st, lease: _Lease) -> None:
        """Pull tasks from the key's queue and pipeline them to this
        worker: queued specs coalesce into batched ``push_tasks`` frames
        (task_submit_batch_max per frame) that the worker executes in
        order and acks in batch; up to _PUSH_WINDOW unacked specs ride
        the connection across frames.  Mid-frame completions stream back
        early as task_done pushes (resolved via lease.pending), so a
        fast task batched behind a slow one is observable as soon as it
        finishes — batch acks change framing, not completion latency.
        When the queue drains the lease is parked for
        ``lease_keepalive_ms`` before being returned, so back-to-back
        synchronous submissions reuse the warm worker."""
        inflight: deque = deque()   # (batch, future); batch: [(spec, retries)]
        batch_max = max(1, CONFIG.task_submit_batch_max)
        keepalive = max(0.0, CONFIG.lease_keepalive_ms / 1000.0)
        while True:
            while True:
                with lease.plock:
                    budget = self._PUSH_WINDOW - len(lease.pending)
                if budget <= 0:
                    break
                with self._sched_lock:
                    batch = self._drain_batch_locked(st, budget, batch_max)
                if not batch:
                    break
                if _TELEMETRY:
                    _M_PUSH_BATCH.observe(len(batch))
                    t_now = rtm.now()
                    for _spec, _r in batch:
                        t_sub = self._task_tq.pop(_spec["task_id"], None)
                        if t_sub is not None:
                            _M_QUEUE_WAIT.observe((t_now - t_sub) * 1000.0)
                with lease.plock:
                    for spec, retries in batch:
                        lease.pending[spec["task_id"]] = (spec, retries)
                # send failures surface through the future (call_async
                # catches them internally), landing in the dead-worker
                # path below like any mid-task connection loss
                fut = lease.conn.call_async(
                    "push_tasks", {"specs": [s for s, _ in batch]})
                inflight.append((batch, fut))
            if not inflight:
                with self._sched_lock:
                    # closing window: a task may have been enqueued after
                    # our empty-queue read above
                    if st["queue"] and not self._shutdown.is_set():
                        continue
                    if (keepalive <= 0 or self._shutdown.is_set()
                            or lease.conn.closed):
                        st["leases"].remove(lease)
                        break
                    st["idle"] += 1
                    deadline = time.monotonic() + keepalive
                    while not st["queue"] and not self._shutdown.is_set():
                        t = deadline - time.monotonic()
                        if t <= 0:
                            break
                        self._sched_cv.wait(t)
                    st["idle"] -= 1
                    if (st["queue"] and not self._shutdown.is_set()
                            and not lease.conn.closed):
                        continue
                    st["leases"].remove(lease)
                break
            batch, fut = inflight.popleft()
            try:
                reply = fut.result(None)
            except rpc.RemoteError as e:
                # dispatch-level failure of the whole frame (user task
                # errors come back per-spec, not as RemoteError): fail its
                # unresolved specs; the connection is healthy and keeps
                # serving
                for spec, _retries in batch:
                    if self._lease_unresolve(lease, spec) is not None:
                        self._store_task_error(spec, exc.RayTpuError(str(e)))
                continue
            except (ConnectionError, OSError) as e:
                # Worker died mid-flight. It drains its FIFO serially, so
                # of the unresolved specs (send order — task_done pushes
                # already resolved everything that finished) only the
                # FIRST is charged retry/OOM budget; the rest requeue
                # free.  Send order approximates execution order: a ref-
                # carrying spec resolving args slowly can be overtaken in
                # the executor FIFO by a younger ref-free frame — the
                # same approximation the per-push-thread path always made
                # (pipelined pushes rode independent dispatch threads).
                # Drain the connection's push backlog first: a task_done
                # delivered just before the death must resolve its spec,
                # not be charged as a worker crash.
                try:
                    lease.conn.drain_pushes()
                except Exception:
                    pass
                with lease.plock:
                    remaining = list(lease.pending.values())
                    lease.pending.clear()
                oom = (self._lease_was_oom_killed(lease) if remaining
                       else False)
                if remaining:
                    with self._sched_lock:
                        for s, r in reversed(remaining[1:]):
                            st["queue"].appendleft((s, r))
                        # wake parked keepalive leases: _maybe_request_
                        # lease relies on them having been notified when
                        # it declines to open a lease for a short queue
                        self._sched_cv.notify_all()
                    spec, retries = remaining[0]
                    self._retry_or_fail_dead_worker(key, st, spec,
                                                    retries, oom, e,
                                                    lease.worker_id)
                with self._sched_lock:
                    st["leases"].remove(lease)
                try:
                    lease.conn.close()
                except Exception:
                    pass
                self._maybe_request_lease(key, st)
                return
            else:
                self._consume_batch_reply(lease, batch, reply)
        self._return_lease(lease)
        self._maybe_request_lease(key, st)

    def _lease_unresolve(self, lease: _Lease, spec) -> Optional[tuple]:
        """Claim a spec for resolution: pops its pending entry exactly
        once (None when a task_done push already resolved it)."""
        with lease.plock:
            return lease.pending.pop(spec["task_id"], None)

    def _lease_task_done(self, lease: _Lease, payload: dict) -> None:
        """Streamed per-task completion (worker push, ahead of the frame
        ack).  Runs on the lease connection's serial push thread."""
        with lease.plock:
            item = lease.pending.pop(payload["task_id"], None)
        if item is None:
            return
        self._apply_task_result(item[0], payload["res"])

    def _apply_task_result(self, spec, res: dict) -> None:
        err = res.get("err")
        if err is not None:
            self._store_task_error(spec, exc.RayTpuError(err))
        else:
            self._on_task_reply(spec, res["ok"])

    def _consume_batch_reply(self, lease: _Lease, batch: list,
                             reply: dict) -> None:
        """Resolve one acked push_tasks frame: per-spec results in frame
        order, skipping specs a task_done push resolved early."""
        results = reply["results"]
        for (spec, _retries), res in zip(batch, results):
            if self._lease_unresolve(lease, spec) is not None:
                self._apply_task_result(spec, res)
        # a short reply (worker bug) must not strand the tail's owners
        for spec, _retries in batch[len(results):]:
            if self._lease_unresolve(lease, spec) is not None:
                self._store_task_error(spec, exc.RayTpuError(
                    f"worker returned no result for task "
                    f"{spec.get('name', '')}"))

    def _retry_or_fail_dead_worker(self, key, st, spec, retries: int,
                                   oom: bool, e: BaseException,
                                   worker_id: Optional[str] = None
                                   ) -> None:
        """Retry accounting for one task whose worker died mid-flight.
        An OOM kill draws from its own retry budget (task_oom_retries)
        and leaves max_retries untouched — the task didn't fail, the
        node ran dry.  ``worker_id`` (the dead worker) becomes the
        propagated error's ``dossier_id``, so ``.debug_dossier()`` at
        the driver can pull the crash forensics the raylet harvested."""
        if oom:
            left = self._oom_retries.get(spec["task_id"],
                                         CONFIG.task_oom_retries)
            if left > 0:
                self._oom_retries[spec["task_id"]] = left - 1
                logger.info("task %s OOM-killed; retrying (%d OOM "
                            "retries left)", spec["name"], left - 1)
                with self._sched_lock:
                    st["queue"].appendleft((spec, retries))
                    self._sched_cv.notify_all()  # wake parked leases
            else:
                oom_err = exc.OutOfMemoryError(
                    f"task {spec['name']} was OOM-killed "
                    f"{CONFIG.task_oom_retries + 1} times "
                    f"(host memory exhausted)")
                oom_err.dossier_id = worker_id
                self._store_task_error(spec, oom_err,
                                       error_code=ser.ERROR_OOM)
        elif retries > 0:
            logger.info("task %s worker died; retrying (%d left)",
                        spec["name"], retries)

            def _requeue():
                with self._sched_lock:
                    st["queue"].appendleft((spec, retries - 1))
                    self._sched_cv.notify_all()  # wake parked leases
                # a DELAYED requeue lands after the dead lease's
                # teardown already ran its _maybe_request_lease against
                # an empty queue — without this, no lease-request loop
                # exists to consume the spec and it strands forever
                self._maybe_request_lease(key, st)

            delay_ms = CONFIG.task_retry_delay_ms
            if delay_ms > 0:
                # optional backoff before resubmission (a crash-looping
                # task must not spin the lease machinery at full rate);
                # 0 (default) requeues immediately.  Daemon timer: a
                # pending requeue must not block interpreter exit nor
                # fire into a torn-down scheduler after shutdown.
                t = threading.Timer(delay_ms / 1000.0, _requeue)
                t.daemon = True
                t.start()
            else:
                _requeue()
        else:
            self._store_task_error(spec, exc.WorkerCrashedError(
                f"task {spec['name']} worker died: {e}",
                dossier_id=worker_id))

    def _lease_was_oom_killed(self, lease: _Lease) -> bool:
        payload = {"worker_id": lease.worker_id}
        try:
            if lease.granting_addr is None:
                reply = self._raylet.call("was_oom_killed", payload,
                                          timeout=5)
            else:
                conn = rpc.connect(tuple(lease.granting_addr), timeout=5.0)
                try:
                    reply = conn.call("was_oom_killed", payload, timeout=5)
                finally:
                    conn.close()
            return bool(reply.get("oom"))
        except (ConnectionError, rpc.RpcError, TimeoutError, OSError):
            return False

    def _return_lease(self, lease: _Lease) -> None:
        payload = {"lease_id": lease.lease_id,
                   "worker_id": lease.worker_id,
                   "key": lease.key}
        try:
            if lease.granting_addr is None:
                self._raylet.call("return_worker", payload, timeout=10)
            else:
                conn = rpc.connect(tuple(lease.granting_addr))
                try:
                    conn.call("return_worker", payload, timeout=10)
                finally:
                    conn.close()
        except (ConnectionError, rpc.RemoteError, TimeoutError, OSError):
            pass
        try:
            lease.conn.close()
        except Exception:
            pass

    def _on_task_reply(self, spec, reply) -> None:
        task_id = TaskID(spec["task_id"])
        t0 = self._task_t0.pop(spec["task_id"], None)
        self._task_tq.pop(spec["task_id"], None)
        if t0 is not None:
            _M_TASK_E2E.observe_since(t0)
        results = reply["results"]
        freed: List[Tuple[ObjectID, set]] = []
        with self._owned_lock:
            # arg refs stay pinned while the task's lineage is retained:
            # a reconstruction resubmits the same arg blob, so the owner
            # must not free argument objects earlier (reference: lineage
            # pinning keeps dependency refs alive, reference_count.h)
            if spec["task_id"] not in self._lineage_meta:
                self._arg_refs.pop(spec["task_id"], None)
            self._oom_retries.pop(spec["task_id"], None)
            for i, result in enumerate(results):
                oid = ObjectID.for_task_return(task_id, i)
                entry = self._owned.get(oid)
                if entry is None:
                    continue
                if "dynamic" in result:
                    # num_returns="dynamic": adopt ownership of each yielded
                    # object (slots 1..N) and resolve slot 0 to the
                    # generator of their refs
                    refs = self._adopt_dynamic_returns_locked(
                        task_id, entry, result["dynamic"])
                    entry.dynamic_children = [r.id for r in refs]
                    head, views = ser.serialize(ObjectRefGenerator(refs))
                    entry.data = ser.to_flat_bytes(head, views)
                    entry.error = 0
                    self._memory_cache.pop(oid, None)
                elif "streaming" in result:
                    # completion sentinel of a num_returns="streaming"
                    # task: items 1..N were adopted eagerly as their
                    # reports arrived; slot 0 resolves to the full
                    # ObjectRefGenerator (the ``completed()`` value) and
                    # anchors the items' cleanup as dynamic children
                    n = result["streaming"]["num_items"]
                    children = [ObjectID.for_task_return(task_id, j + 1)
                                for j in range(n)]
                    entry.dynamic_children = list(children)
                    refs = [ObjectRef(c, self.address, None)
                            for c in children]
                    head, views = ser.serialize(ObjectRefGenerator(refs))
                    entry.data = ser.to_flat_bytes(head, views)
                    entry.error = 0
                    self._memory_cache.pop(oid, None)
                else:
                    entry.error = result.get("error", 0)
                    if result.get("data") is not None:
                        entry.data = result["data"]
                        self._memory_cache.pop(oid, None)
                    else:
                        entry.locations.add(result["location"])
                        entry.size = int(result.get("size", 0))
                entry.state = "ready"
                entry.event.set()
                # the last user ref may have been dropped while this slot
                # was pending (e.g. mid-reconstruction): free now, or the
                # entry and its unevictable primary copy leak forever
                if entry.refcount <= 0:
                    self._free_with_children_locked(oid, entry, freed)
            # a completion may unblock FIFO lineage eviction that a pending
            # head task was holding up at submit time
            self._evict_lineage_locked()
        self._complete_frees(freed)
        failed = any(r.get("error") for r in results)
        if spec.get("num_returns") == "streaming":
            total = next((r["streaming"]["num_items"] for r in results
                          if "streaming" in r), None)
            self._stream_finished(spec["task_id"], failed=failed,
                                  total=total)
        self.events.record(task_id.hex(), "FAILED" if failed else "FINISHED",
                           name=spec["name"])

    def _adopt_dynamic_returns_locked(self, task_id: TaskID, slot0_entry,
                                      sub_results) -> List[ObjectRef]:
        refs = []
        lmeta = self._lineage_meta.get(task_id.binary())
        for j, sub in enumerate(sub_results):
            sub_oid = ObjectID.for_task_return(task_id, j + 1)
            sub_entry = self._owned.get(sub_oid)
            if sub_entry is None:
                sub_entry = _OwnedObject()
                # re-running the task regenerates every dynamic return
                sub_entry.task_spec = slot0_entry.task_spec
                self._owned[sub_oid] = sub_entry
            if lmeta is not None:
                lmeta["slots"].add(sub_oid)
            sub_entry.error = sub.get("error", 0)
            if sub.get("data") is not None:
                sub_entry.data = sub["data"]
            else:
                sub_entry.locations.add(sub["location"])
                sub_entry.size = int(sub.get("size", 0))
            sub_entry.state = "ready"
            sub_entry.event.set()
            # unbound refs (worker=None): these only exist to be serialized
            # into slot 0 — binding them would register/unregister a local
            # refcount whose drop-to-zero frees the entry before the caller
            # ever deserializes the generator
            refs.append(ObjectRef(sub_oid, self.address, None))
        return refs

    # ------------------------------------------- streaming generators
    # Owner side of num_returns="streaming" (docs/streaming_generators.md):
    # the executing worker reports every yield as a report_generator_item
    # RPC on this worker's server; each item is adopted into the owned
    # table the moment it arrives, the consumer's next() advances a
    # strict index cursor, and backpressure is the withheld report reply
    # (a parked Deferred resolves when ITS item is consumed, so the
    # producer's unacked window equals the unconsumed in-flight count).

    def _register_stream(self, task_binary: bytes, bp: int) -> _StreamState:
        state = _StreamState(task_binary, bp)
        with self._streams_lock:
            self._streams[task_binary] = state
        return state

    def make_streaming_generator(self, ref: "ObjectRef"
                                 ) -> StreamingObjectRefGenerator:
        """Wrap a streaming task's slot-0 ref (its stream was registered
        at submit time) into the consumer-facing generator."""
        with self._streams_lock:
            state = self._streams[ref.id.task_id().binary()]
        return StreamingObjectRefGenerator(self, state, ref)

    def _rpc_report_generator_item(self, p: dict):
        """One yielded item from the executing worker: adopt ownership
        eagerly (data inline or a shm location, exactly like a dynamic
        child) and answer with consumption credit — immediately when the
        backpressure window allows, else a Deferred parked until the
        consumer reaches this item.  Replayed items (a retried worker
        re-yielding an already-consumed prefix) ack immediately."""
        tb = p["task_id"]
        idx = p["index"]
        with self._streams_lock:
            state = self._streams.get(tb)
        if state is None:
            return {"cancel": True}   # consumer dropped the generator
        with state.cv:
            if state.closed:
                # checked BEFORE adoption so a post-close report doesn't
                # recreate entries _close_stream just freed (a racing
                # close still gets them swept at task completion via
                # slot 0's dynamic_children)
                return {"cancel": True}
        task_id = TaskID(tb)
        oid = ObjectID.for_task_return(task_id, idx + 1)
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is None:
                entry = _OwnedObject()
                slot0 = self._owned.get(
                    ObjectID.for_task_return(task_id, 0))
                if slot0 is not None:
                    # re-running the task regenerates every item
                    entry.task_spec = slot0.task_spec
                self._owned[oid] = entry
                lmeta = self._lineage_meta.get(tb)
                if lmeta is not None:
                    lmeta["slots"].add(oid)
            if entry.state != "ready":
                entry.error = p.get("error", 0)
                if p.get("data") is not None:
                    entry.data = p["data"]
                else:
                    entry.locations.add(p["location"])
                    entry.size = int(p.get("size", 0))
                entry.state = "ready"
                entry.event.set()
            elif p.get("location"):
                # replay from a retried worker: the fresh copy's node may
                # differ from the (possibly dead) original — record it so
                # the consumer's fetch finds the live copy instead of
                # burning another reconstruction
                entry.locations.add(p["location"])
        _M_STREAM_ITEMS.inc()
        with state.cv:
            if state.closed:
                return {"cancel": True}
            if idx >= state.consumed:
                state.arrived.add(idx)
            state.max_unconsumed = max(state.max_unconsumed,
                                       len(state.arrived))
            self._stream_wake(state)
            if state.bp > 0 and idx >= state.consumed:
                d = rpc.Deferred()
                state.parked.append((idx, d, rtm.now()))
                _M_STREAM_STALLS.inc()
                return d
            return {"consumed": state.consumed}

    @staticmethod
    def _stream_wake(state: _StreamState) -> None:
        """Wake both consumer styles; call with ``state.cv`` held."""
        state.cv.notify_all()
        if state.waiters:
            waiters, state.waiters = state.waiters, []
            for cb in waiters:
                try:
                    cb()          # only schedules a loop callback
                except Exception:
                    pass

    def _stream_add_waiter(self, state: _StreamState, cb) -> None:
        """Register a one-shot state-change callback for an async
        consumer; fires immediately when progress is already available
        (the caller loops and re-tries the claim)."""
        with state.cv:
            ready = (state.consumed in state.arrived or state.failed
                     or state.closed
                     or (state.total is not None
                         and state.consumed >= state.total))
            if not ready:
                state.waiters.append(cb)
                return
        try:
            cb()
        except Exception:
            pass

    def _stream_try_next(self, state: _StreamState, ref: "ObjectRef"):
        """Non-blocking next(): the next item's ObjectRef,
        _StreamExhausted at end of stream, None when nothing is
        available yet, or raises the stream's terminal error — the
        claim half of _stream_next without the cv wait (async
        consumers interleave it with _stream_add_waiter)."""
        resolve: List = []
        failed = False
        with state.cv:
            idx = state.consumed
            if idx in state.arrived:
                state.arrived.discard(idx)
                state.consumed = idx + 1
                resolve = [(d, t) for i, d, t in state.parked
                           if i < state.consumed]
                state.parked = [p for p in state.parked
                                if p[0] >= state.consumed]
            elif state.total is not None and idx >= state.total:
                return _StreamExhausted
            elif state.failed:
                failed = True
            elif state.closed:
                raise exc.RayTpuError("streaming generator was closed")
            else:
                return None
        for d, t_parked in resolve:
            _M_STREAM_PARKED.observe_since(t_parked)
            d.resolve({"consumed": state.consumed})
        if failed:
            # slot 0 holds the task's error payload: get() raises it
            # (the terminal reply that set ``failed`` also readied it)
            self.get([ref])
            raise exc.RayTpuError(
                "streaming generator task failed")  # unreachable backstop
        oid = ObjectID.for_task_return(TaskID(state.task_binary),
                                       idx + 1)    # item j at slot j+1
        return ObjectRef(oid, self.address, self)

    def _stream_next(self, state: _StreamState, ref: "ObjectRef",
                     timeout: Optional[float] = None):
        """Blocking next(): the ObjectRef of the next item in index
        order, _StreamExhausted at end of stream, or the task's error
        (raised) once the stream failed and every delivered item has
        been consumed.  Consuming resolves parked producer reports."""
        deadline = None if timeout is None else time.monotonic() + timeout
        resolve: List = []
        failed = False
        claimed = -1
        with state.cv:
            while True:
                idx = state.consumed
                if idx in state.arrived:
                    state.arrived.discard(idx)
                    # claim THIS index under the lock: a concurrent
                    # consumer may advance state.consumed again before
                    # we build the ref below
                    claimed = idx
                    state.consumed = idx + 1
                    resolve = [(d, t) for i, d, t in state.parked
                               if i < state.consumed]
                    state.parked = [p for p in state.parked
                                    if p[0] >= state.consumed]
                    break
                if state.total is not None and idx >= state.total:
                    return _StreamExhausted
                if state.failed:
                    failed = True
                    break
                if state.closed:
                    raise exc.RayTpuError(
                        "streaming generator was closed")
                t = self._remaining(deadline)
                if not state.cv.wait(t if t is not None else 5.0) \
                        and deadline is not None \
                        and time.monotonic() >= deadline:
                    raise exc.GetTimeoutError(
                        "timed out waiting for the next generator item")
        for d, t_parked in resolve:
            _M_STREAM_PARKED.observe_since(t_parked)
            d.resolve({"consumed": state.consumed})
        if failed:
            # slot 0 holds the task's error payload: get() raises it
            self.get([ref])
            raise exc.RayTpuError(
                "streaming generator task failed")  # unreachable backstop
        oid = ObjectID.for_task_return(TaskID(state.task_binary),
                                       claimed + 1)  # item j at slot j+1
        return ObjectRef(oid, self.address, self)

    def _stream_finished(self, task_binary: bytes, *, failed: bool,
                         total: Optional[int] = None) -> None:
        """Terminal task outcome reached the owner: wake the consumer.
        A retryable worker death never lands here — the stream stays
        open and the re-executed task replays its items."""
        with self._streams_lock:
            state = self._streams.get(task_binary)
        if state is None:
            return
        resolve: List = []
        with state.cv:
            if failed:
                state.failed = True
            else:
                state.total = total
                # late credit: items past the consumer's cursor can no
                # longer arrive, so nothing is parked for a reason
                resolve = [d for _i, d, _t in state.parked]
                state.parked = []
            self._stream_wake(state)
        for d in resolve:
            d.resolve({"consumed": state.consumed})

    def _close_stream(self, state: _StreamState) -> None:
        """Consumer dropped the generator: cancel parked reports (the
        worker stops iterating), drop the table entry, and free
        arrived-but-unconsumed item objects."""
        with state.cv:
            if state.closed:
                return
            state.closed = True
            parked, state.parked = state.parked, []
            orphans = list(state.arrived)
            state.arrived.clear()
            self._stream_wake(state)
        for _i, d, _t in parked:
            d.resolve({"cancel": True})
        with self._streams_lock:
            self._streams.pop(state.task_binary, None)
        if self._shutdown.is_set():
            return
        task_id = TaskID(state.task_binary)
        freed: List[Tuple[ObjectID, set]] = []
        with self._owned_lock:
            for idx in orphans:
                oid = ObjectID.for_task_return(task_id, idx + 1)
                entry = self._owned.get(oid)
                if entry is not None and entry.refcount <= 0 \
                        and entry.state == "ready":
                    self._free_entry_locked(oid, entry, freed)
        self._complete_frees(freed)

    def prepare_runtime_env(self, raw: Optional[dict]) -> Optional[dict]:
        """Package+upload a raw runtime_env; memoised on the spec plus a
        cheap mtime/size fingerprint of any local paths, so edits to a
        working_dir between submits re-upload instead of serving stale
        code, while unchanged trees skip the zip+upload entirely."""
        if not raw:
            return None
        import json as _json
        from ray_tpu.runtime_env.packaging import tree_fingerprint
        paths = list(raw.get("py_modules") or [])
        if raw.get("working_dir"):
            paths.append(raw["working_dir"])
        cache_key = _json.dumps(
            [dict(raw), [tree_fingerprint(p) for p in paths]],
            sort_keys=True, default=str)
        if cache_key not in self._runtime_env_cache:
            from ray_tpu.runtime_env import prepare_runtime_env as _prep
            self._runtime_env_cache[cache_key] = _prep(raw, self.gcs)
        return self._runtime_env_cache[cache_key]

    # --------------------------------------------------------------- actors
    def create_actor(self, cls, args, kwargs, *, name: Optional[str] = None,
                     namespace: str = "", detached: bool = False,
                     max_restarts: int = 0,
                     max_concurrency: Optional[int] = None,
                     concurrency_groups: Optional[Dict[str, int]] = None,
                     resources: Optional[Dict[str, float]] = None,
                     scheduling_strategy: Optional[dict] = None,
                     runtime_env: Optional[dict] = None,
                     cls_key: Optional[str] = None,
                     language: Optional[str] = None) -> "ActorID":
        actor_id = ActorID.from_random()
        bundle = None
        strategy = None
        if scheduling_strategy:
            if scheduling_strategy.get("type") == "placement_group":
                bundle = [scheduling_strategy["pg_id"],
                          int(scheduling_strategy.get("bundle_index", -1))]
            else:
                # node_affinity / spread: enforced by the GCS scheduler
                strategy = dict(scheduling_strategy)
        # cross-language actors carry a pre-resolved class key the target
        # language's worker resolves in its own registry
        if cls_key is None:
            cls_key = self.register_function(cls)
        creation_spec = cloudpickle.dumps({
            "actor_id": actor_id.binary(),
            "cls_key": cls_key,
            "args": self._serialize_args_tracked(args, kwargs,
                                                 TaskID.from_random()),
            "owner_addr": list(self.address),
            "max_concurrency": max_concurrency,
            "concurrency_groups": dict(concurrency_groups or {}),
        })
        self.gcs.call("register_actor", {
            "actor_id": actor_id.hex(),
            "caller_node_id": self.node_id,
            "job_id": self.job_id.hex(),
            "name": name,
            "namespace": namespace,
            "detached": detached,
            "spec": creation_spec,
            "resources": dict(resources or {}),
            "max_restarts": max_restarts,
            "bundle": bundle,
            "strategy": strategy,
            "runtime_env": runtime_env or self.job_runtime_env,
            "language": language,
        }, timeout=CONFIG.actor_creation_timeout_s)
        return actor_id

    def _resolve_actor(self, actor_id_hex: str,
                       timeout: Optional[float] = None) -> Tuple[str, int]:
        deadline = time.monotonic() + (timeout or
                                       CONFIG.actor_creation_timeout_s)
        # adaptive poll: tight at first (creation is ~100 ms on an idle
        # node; a fixed 20 ms tick added a quantization stall on every
        # first call), backing off so 1k pending resolvers don't melt
        # the GCS during mass creation
        delay = 0.003
        while True:
            info = self.gcs.call("get_actor", {"actor_id": actor_id_hex})
            if info is None:
                raise exc.ActorDiedError(f"actor {actor_id_hex[:8]} not found")
            if info["state"] == ALIVE and info["address"]:
                return tuple(info["address"])
            if info["state"] == DEAD:
                raise exc.ActorDiedError(
                    info.get("death_cause") or "actor is dead",
                    dossier_id=info.get("death_worker_id"))
            if time.monotonic() > deadline:
                raise exc.ActorUnavailableError(
                    f"actor {actor_id_hex[:8]} not ready "
                    f"(state={info['state']})")
            time.sleep(delay)
            delay = min(delay * 1.6, 0.05)

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: tuple, kwargs: dict, *,
                          num_returns: int = 1,
                          max_task_retries: int = 0,
                          concurrency_group: Optional[str] = None
                          ) -> List[ObjectRef]:
        num_returns = normalize_num_returns(num_returns)
        _M_ACTOR_SUBMITS.inc()
        task_id = TaskID.from_random()
        aid = actor_id.hex()
        spec = {
            "task_id": task_id.binary(),
            "actor_id": aid,
            "method": method_name,
            "args": self._serialize_args_tracked(args, kwargs, task_id),
            "num_returns": num_returns,
            "owner_addr": list(self.address),
            "name": method_name,
        }
        if concurrency_group:
            spec["group"] = concurrency_group
        if num_returns == "streaming":
            spec["backpressure"] = CONFIG.generator_backpressure_num_objects
            self._register_stream(task_id.binary(), spec["backpressure"])
        trace_ctx = _submit_trace_ctx(method_name)
        if trace_ctx:
            spec["trace_ctx"] = trace_ctx
        refs = []
        with self._owned_lock:
            for i in range(num_return_slots(num_returns)):
                oid = ObjectID.for_task_return(task_id, i)
                self._owned[oid] = _OwnedObject()
                refs.append(ObjectRef(oid, self.address, self))
        with self._actor_lock:
            pipe = self._actor_pipes.get(aid)
            if pipe is None:
                pipe = _ActorPipe(self, aid)
                self._actor_pipes[aid] = pipe
        if _TELEMETRY:
            self._task_t0[task_id.binary()] = rtm.now()
        pipe.enqueue(spec, max_task_retries)
        self.events.record(task_id.hex(), "SUBMITTED", name=method_name,
                           actor_id=aid)
        return refs

    def _store_actor_error(self, spec, error: BaseException) -> None:
        task_id = TaskID(spec["task_id"])
        self._arg_refs.pop(spec["task_id"], None)
        t0 = self._task_t0.pop(spec["task_id"], None)
        self._task_tq.pop(spec["task_id"], None)
        if t0 is not None:
            _M_TASK_E2E.observe_since(t0)
        self.events.record(task_id.hex(), "FAILED", name=spec.get("name", ""),
                           actor_id=spec.get("actor_id", ""),
                           error_type=type(error).__name__)
        head, views = ser.serialize(error, error_type=ser.ERROR_ACTOR_DIED)
        data = ser.to_flat_bytes(head, views)
        freed: List[Tuple[ObjectID, set]] = []
        with self._owned_lock:
            for i in range(num_return_slots(spec["num_returns"])):
                oid = ObjectID.for_task_return(task_id, i)
                entry = self._owned.get(oid)
                if entry is not None:
                    entry.data = data
                    entry.state = "ready"
                    entry.error = ser.ERROR_ACTOR_DIED
                    entry.event.set()
                    if entry.refcount <= 0:
                        self._free_entry_locked(oid, entry, freed)
        self._complete_frees(freed)
        if spec.get("num_returns") == "streaming":
            self._stream_finished(spec["task_id"], failed=True)

    def kill_actor(self, actor_id: ActorID) -> None:
        self.gcs.call("kill_actor", {"actor_id": actor_id.hex()})
        # The GCS marks the actor DEAD before replying, but our pipe may
        # still hold a live connection to the (not-yet-exited) worker —
        # sever it so calls submitted after kill() returns deterministically
        # re-resolve via the GCS and fail with ActorDiedError instead of
        # racing the worker's exit.
        with self._actor_lock:
            pipe = self._actor_pipes.get(actor_id.hex())
        if pipe is not None:
            with pipe.cv:
                conn = pipe.conn
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    # ----------------------------------------------------------- rpc server
    def _handle_rpc(self, conn: rpc.Connection, method: str, p: Any) -> Any:
        if method == "get_object":
            return self._rpc_get_object(p or {})
        if method == "report_generator_item":
            return self._rpc_report_generator_item(p or {})
        if method == "report_object_location":
            return self._rpc_report_object_location(p or {})
        if method == "core_worker_stats":
            return self._rpc_core_worker_stats(p or {})
        if method == "profile":
            # drivers flame-sample like any worker (`ray-tpu profile`);
            # "device" requests the gang-capture dict (host stacks +
            # jax.profiler device trace when on TPU)
            from ray_tpu._private.profiler import (profile_capture,
                                                   sample_folded)
            p = p or {}
            if "device" in p:
                return profile_capture(float(p.get("duration", 2.0)),
                                       device=bool(p.get("device")))
            return sample_folded(float(p.get("duration", 2.0)))
        if method == "dump_stacks":
            from ray_tpu._private.profiler import dump_stacks, \
                sample_folded
            return {"threads": dump_stacks(),
                    "folded": sample_folded(
                        float((p or {}).get("duration", 0.2)))}
        raise rpc.RpcError(f"core_worker: unknown method {method}")

    def _rpc_core_worker_stats(self, p) -> dict:
        """Owned-object + submission introspection for the state API's
        `list objects` / `memory` views (cf. reference
        CoreWorkerService.GetCoreWorkerStats, core_worker.proto)."""
        objects = []
        with self._owned_lock:
            for oid, entry in self._owned.items():
                objects.append({
                    "object_id": oid.hex(),
                    "state": entry.state,
                    "refcount": entry.refcount,
                    "size": len(entry.data) if entry.data is not None else 0,
                    "inline": entry.data is not None,
                    "locations": sorted(entry.locations),
                })
        with self._sched_lock:
            pending = sum(len(s["queue"]) for s in self._sched.values())
            leases = sum(len(s["leases"]) for s in self._sched.values())
        return {
            "worker_id": self.worker_id.hex(),
            "job_id": self.job_id.hex(),
            "mode": self.mode,
            "address": list(self.address),
            "num_owned_objects": len(objects),
            "objects": objects,
            "pending_tasks": pending,
            "active_leases": leases,
        }

    def _rpc_report_object_location(self, p) -> dict:
        """A borrower (or a raylet prefetch) published a pulled copy of
        an object we own into its node's shm — the ownership directory's
        OnObjectLocationAdded analog.  Growing the location set lets
        later pulls stripe across the new copy and the final free sweep
        it; a report for an unknown/inline object is a no-op."""
        oid = ObjectID(p["object_id"])
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is not None and entry.data is None:
                entry.locations.add(p["node_id"])
                if not entry.size and p.get("size"):
                    entry.size = int(p["size"])
        return {"ok": True}

    def _rpc_get_object(self, p) -> Optional[dict]:
        """Owner side of borrower gets: inline data or known locations."""
        oid = ObjectID(p["object_id"])
        timeout = p.get("timeout", 0.0)
        with self._owned_lock:
            entry = self._owned.get(oid)
        if entry is None:
            # maybe it's in our local shm even if not owned
            if self.store.contains(oid):
                res = self.store.get(oid, timeout=0.0)
                if res is not None:
                    buf, _ = res
                    try:
                        return {"data": bytes(buf)}
                    finally:
                        buf.release()
                        self.store.release(oid)
            return None
        if not entry.event.wait(timeout):
            return None
        if p.get("probe"):
            return {"ready": True}
        if entry.data is not None:
            return {"data": entry.data}
        locations = self._prune_dead_locations(entry)
        if not locations:
            # every copy died with its node: recover (or resolve the entry
            # to ObjectLostError) off the RPC thread; the borrower keeps
            # polling and picks up the recomputed value / error. One
            # recovery thread per entry — concurrent borrower polls (every
            # 10 ms each) must not fan out redundant ones.
            with self._owned_lock:
                spawn = not entry.recovering
                entry.recovering = True
            if spawn:
                try:
                    threading.Thread(target=self._recover_or_fail,
                                     args=(oid, entry), daemon=True).start()
                except RuntimeError:  # thread exhaustion: let a later
                    with self._owned_lock:  # borrower poll retry the spawn
                        entry.recovering = False
            return None
        return {"locations": list(locations)}

    # -------------------------------------------------------------- events
    def task_events(self) -> List[dict]:
        return self.events.snapshot()


class _ActorPipe:
    """Ordered, pipelined submission channel to one actor.

    A single sender thread drains the FIFO, assigning sequence numbers in
    submission order and issuing async calls without waiting (pipelining).
    On connection loss: unsent + retryable in-flight tasks are resubmitted in
    order on a fresh stream once the actor is ALIVE again; non-retryable
    in-flight tasks fail (reference semantics: actor tasks are not retried
    unless max_task_retries > 0)."""

    def __init__(self, core: "CoreWorker", actor_id_hex: str):
        self.core = core
        self.aid = actor_id_hex
        self.queue: deque = deque()          # (spec, retries)
        self.inflight: Dict[int, tuple] = {}  # seq -> (spec, retries)
        self.cv = threading.Condition()
        self.conn: Optional[rpc.Connection] = None
        self.next_seq = 0
        self.stream = ""
        self.broken = False
        # sender thread holds a popped spec whose seq isn't assigned yet:
        # the inline fast path must not overtake it (seq = submission
        # order is the actor ordering guarantee)
        self.draining = 0
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def enqueue(self, spec, retries: int) -> None:
        with self.cv:
            if (self.conn is None or self.conn.closed or self.broken
                    or self.queue or self.draining):
                # cold/broken/backed-up pipe: the sender thread plans it
                self.queue.append((spec, retries))
                self.cv.notify()
                return
            # warm idle pipe: assign the seq and send from the caller's
            # thread — skips a sender-thread wake per call.  Wire order
            # may interleave with a concurrent inline sender, but seqs
            # are assigned under cv in submission order and the worker
            # executes by seq, so ordering holds.
            conn, seq, spec = self._assign_locked(spec, retries)
        self._send_assigned(conn, seq, spec)

    def _loop(self) -> None:
        while True:
            with self.cv:
                while not self.queue and not self.broken:
                    self.cv.wait()
                if self.broken:
                    self._handle_break_locked()
                    continue
                spec, retries = self.queue.popleft()
                self.draining += 1
            try:
                try:
                    ok = self._ensure_conn(spec)
                except (ConnectionError, OSError, TimeoutError):
                    # the resolved address can be stale mid-restart (the
                    # GCS may answer ALIVE with the dying worker's
                    # address for a beat): requeue and retry.  This must
                    # NOT escape — an uncaught connect error here kills
                    # the only sender thread and every later call on the
                    # pipe hangs to its get() timeout.
                    with self.cv:
                        self.queue.appendleft((spec, retries))
                    time.sleep(0.2)
                    continue
                if not ok:
                    continue
                with self.cv:
                    conn, seq, spec = self._assign_locked(spec, retries)
            finally:
                with self.cv:
                    self.draining -= 1
            self._send_assigned(conn, seq, spec)

    def _assign_locked(self, spec, retries: int):
        """cv held: stamp the next seq + current stream onto the spec
        and register it in-flight.  Both send paths (inline enqueue and
        the sender thread) MUST come through here — the stream stamp is
        what lets _on_done distinguish a stale-connection failure from a
        live break."""
        seq = self.next_seq
        self.next_seq += 1
        spec = dict(spec, seq=seq, stream=self.stream)
        self.inflight[seq] = (spec, retries)
        return self.conn, seq, spec

    def _send_assigned(self, conn, seq: int, spec) -> None:
        fut = conn.call_async("actor_task", spec)
        fut.add_done_callback(
            lambda f, s=seq, sp=spec: self._on_done(s, sp, f))

    def _ensure_conn(self, spec) -> bool:
        """True when a live connection is bound.  Raises ConnectionError/
        OSError on a transient connect failure (caller retries); returns
        False after failing the pipe's work on a permanent actor error."""
        with self.cv:
            if self.conn is not None and not self.conn.closed:
                return True
        try:
            addr = self.core._resolve_actor(self.aid)
        except exc.RayTpuError as e:
            self.core._store_actor_error(spec, e)
            # fail everything queued: the actor is gone for good
            with self.cv:
                dead = list(self.queue)
                self.queue.clear()
            for sp, _ in dead:
                self.core._store_actor_error(sp, e)
            return False
        conn = rpc.connect(addr)
        with self.cv:
            self.conn = conn
            self.stream = WorkerID.from_random().hex()[:16]
            self.next_seq = 0
        return True

    def _on_done(self, seq: int, spec, fut) -> None:
        try:
            reply = fut.result()
        except (ConnectionError, OSError):
            # connection died; the sender thread re-plans everything that
            # was in flight, so just flag the break — but only if this
            # failure belongs to the CURRENT stream.  An inline send can
            # race break recovery: its call_async lands on the old closed
            # conn after _handle_break_locked already re-planned that
            # stream (including this seq) onto a fresh connection, and
            # re-flagging would tear the healthy replacement down.
            with self.cv:
                if spec.get("stream") == self.stream:
                    self.broken = True
                    self.cv.notify()
            return
        except rpc.RemoteError as e:
            self.core._store_actor_error(spec, exc.RayTpuError(str(e)))
            with self.cv:
                self.inflight.pop(seq, None)
            return
        with self.cv:
            self.inflight.pop(seq, None)
        self.core._on_task_reply(spec, reply)

    def _handle_break_locked(self) -> None:
        """cv held.  Reset the pipe after a connection loss."""
        if self.conn is not None:
            conn, self.conn = self.conn, None
        else:
            conn = None
        inflight = [self.inflight[s] for s in sorted(self.inflight)]
        self.inflight.clear()
        self.broken = False
        requeue = []
        failed = []
        for spec, retries in inflight:
            base = {k: v for k, v in spec.items()
                    if k not in ("seq", "stream")}
            if retries > 0:
                requeue.append((base, retries - 1))
            else:
                failed.append(base)
        self.queue.extendleft(reversed(requeue))
        # release the lock-free work outside: store errors after cv block by
        # stashing on self (simplest: do it inline; _store_actor_error only
        # touches _owned_lock which is never held while calling here)
        for spec in failed:
            self.core._store_actor_error(spec, exc.ActorUnavailableError(
                f"actor {self.aid[:8]} died while this call was in flight"))
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass


def _current_trace_context() -> dict:
    from ray_tpu.util.tracing.tracing_helper import get_trace_context
    return get_trace_context()


def _submit_trace_ctx(name: str) -> Optional[dict]:
    """Trace context to stamp onto a task/actor spec at submission.

    An active context (a serve ingress root, a user ``span()``, an
    executing task's span) propagates as-is.  With NO active context the
    deterministic sampler may open a fresh trace root for this
    submission (docs/observability.md): the unsampled fast path costs
    one random draw + compare; a sampled one records an instant
    ``submit`` root span so the trace has an anchor whose children are
    the worker-side execution spans."""
    trh = _tracing()
    ctx = trh.current_context()
    if ctx:
        return dict(ctx)
    ctx = trh.maybe_sample_root()
    if ctx is not None:
        trh.record_span({
            "trace_id": ctx["trace_id"], "span_id": ctx["span_id"],
            "name": f"submit:{name}", "kind": "submit",
            "start": time.time(), "dur_ms": 0.0, "status": trh.OK,
            "root": True})
    return ctx


def _maybe_big(value: Any) -> bool:
    """Cheap pre-filter before paying for a pickle size check."""
    import numpy as np
    if isinstance(value, np.ndarray):
        return value.nbytes > CONFIG.max_direct_call_args_bytes
    if isinstance(value, (bytes, bytearray, str)):
        return len(value) > CONFIG.max_direct_call_args_bytes
    return isinstance(value, (list, tuple, dict)) and len(value) > 1000
