"""Node daemon: worker pool, lease-based local scheduler, object serving.

TPU-native analog of the reference raylet
(/root/reference/src/ray/raylet/node_manager.h:144 NodeManager,
worker_pool.h:156 WorkerPool, scheduling/local_task_manager.h:58).  The
worker-lease protocol is the reference's
(NodeManager::HandleRequestWorkerLease node_manager.cc:1883 ->
LocalTaskManager dispatch): a caller leases a worker for a scheduling key,
pushes tasks to it directly (the raylet is off the hot path), and returns the
lease when idle.  Resources are granted at lease time and returned at
lease-return time.

TPU process model (SURVEY.md §7 hard-part 4): a node's TPU chips are exposed
as a ``TPU`` resource, and a worker that leases any TPU count gets exclusive
libtpu ownership via env isolation — exactly one process per host touches the
chips unless ``tpu_chips_per_host`` subdivides visible devices.
"""

from __future__ import annotations

import os
import pickle
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import psutil

from ray_tpu._private import cluster_events as cev
from ray_tpu._private import rpc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import transfer
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.logging_utils import get_logger
from ray_tpu.runtime.gcs import GcsClient
from ray_tpu.runtime.object_store import SharedMemoryStore

# lease-path telemetry (docs/observability.md)
_M_LEASE = rtm.histogram(
    "ray_tpu_lease_grant_ms",
    "lease request queued -> grant latency at this raylet (ms)")
_M_SPAWNS = rtm.counter(
    "ray_tpu_workers_spawned_total", "worker processes spawned")
# data-plane serving + prefetch telemetry (docs/object_transfer.md)
_M_CHUNKS_SERVED = rtm.counter(
    "ray_tpu_chunks_served_total",
    "object chunks served to remote pullers from this node")
_M_CHUNK_BYTES_OUT = rtm.counter(
    "ray_tpu_chunk_bytes_served_total",
    "object bytes served to remote pullers (zero-copy shm slices)")
_M_PREFETCH_REQS = rtm.counter(
    "ray_tpu_prefetch_requests_total",
    "large task arguments a lease request asked this raylet to prefetch")
_M_PREFETCH_HITS = rtm.counter(
    "ray_tpu_prefetch_hits_total",
    "prefetch requests already satisfied by a local copy")
_M_PREFETCH_BYTES = rtm.counter(
    "ray_tpu_prefetch_bytes_total",
    "argument bytes pulled into local shm ahead of task dispatch")
_M_LOCALITY_HITS = rtm.counter(
    "ray_tpu_locality_lease_redirects_total",
    "lease requests redirected to the node holding the most argument "
    "bytes (locality-aware placement)")

logger = get_logger("raylet")


def detect_resources() -> Dict[str, float]:
    resources = {"CPU": float(os.cpu_count() or 1)}
    chips = CONFIG.tpu_chips_per_host
    if chips == 0:
        # detect via env (set on TPU VMs) without importing jax here
        if os.environ.get("TPU_CHIPS_PER_HOST"):
            chips = int(os.environ["TPU_CHIPS_PER_HOST"])
        elif os.environ.get("JAX_PLATFORMS", "").startswith(("tpu", "axon")):
            chips = 1
    if chips:
        resources["TPU"] = float(chips)
    mem = psutil.virtual_memory().total
    resources["memory"] = float(mem)
    return resources


# env vars consumed at interpreter start / first import: a zygote fork
# applies env AFTER those were read, so such overrides must exec.
# JAX_PLATFORMS / XLA_FLAGS are NOT here: they are read at first
# backend init, which the zygote never performs — the forked child
# re-pins the platform explicitly (worker_zygote._become_worker).
_IMPORT_SENSITIVE_ENV = ("LD_", "PYTHON", "TPU_", "PALLAS_", "MALLOC_")


def _env_needs_exec(env_overrides) -> bool:
    return any(k.startswith(_IMPORT_SENSITIVE_ENV)
               for k in (env_overrides or {}))


class ForkedProc:
    """Popen-shaped handle over a zygote-forked worker pid.

    The worker is a direct child of the zygote, which runs with SIGCHLD
    ignored so exits auto-reap (single-fork protocol, worker_zygote.py) —
    there is no exit status for the raylet to collect; returncode is -1
    once the process is gone, which is all the pool logic reads.
    Liveness and signaling go through a pidfd when available: a bare pid
    can be recycled by an unrelated process as soon as the kernel reaps
    it, which would make kill(pid, 0) report a dead worker as alive
    forever."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._pidfd: Optional[int] = None
        try:
            self._pidfd = os.pidfd_open(pid)
        except (OSError, AttributeError):
            pass        # process already gone, or pre-5.3 kernel

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if self._pidfd is not None:
            import select
            r, _, _ = select.select([self._pidfd], [], [], 0)
            if not r:
                return None
            os.close(self._pidfd)
            self._pidfd = None
            self.returncode = -1
            return self.returncode
        try:
            os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            self.returncode = -1
            return self.returncode
        except PermissionError:     # pid recycled by another user: dead
            self.returncode = -1
            return self.returncode

    def _signal(self, sig: int) -> None:
        try:
            if self._pidfd is not None:
                signal.pidfd_send_signal(self._pidfd, sig)
            else:
                os.kill(self.pid, sig)
        except (ProcessLookupError, OSError):
            self.returncode = self.returncode or -1

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def __del__(self):
        if self._pidfd is not None:
            try:
                os.close(self._pidfd)
            except OSError:
                pass

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("zygote-worker", timeout)
            time.sleep(0.02)
        return self.returncode


class _PendingProc:
    """Placeholder while the real process is being spawned: alive to
    poll(), inert to signals — a health sweep racing the spawn must
    neither reap nor signal a worker that doesn't exist yet.  Signals
    received during the window are REMEMBERED so the spawner can apply
    them to the real process the moment it exists (a kill during the
    pending window must not leak a live worker)."""

    pid = -1
    returncode = None

    def __init__(self):
        self.terminated = False

    def poll(self):
        return None

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.terminated = True

    def wait(self, timeout=None):
        return None


class WorkerHandle:
    def __init__(self, worker_id: WorkerID,
                 proc: Optional[subprocess.Popen]):
        self.worker_id = worker_id
        self.proc = proc if proc is not None else _PendingProc()
        self.address: Optional[Tuple[str, int]] = None
        self.conn: Optional[rpc.Connection] = None
        self.ready = threading.Event()
        self.lease_id: Optional[str] = None
        self.actor_id: Optional[str] = None
        self.job_id: Optional[str] = None
        self.last_idle = time.monotonic()
        self.started_at = time.monotonic()


class Raylet:
    def __init__(self, gcs_address: Tuple[str, int],
                 session_dir: str,
                 node_id: Optional[NodeID] = None,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None,
                 host: str = "127.0.0.1",
                 labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id or NodeID.from_random()
        self.session_dir = session_dir
        os.makedirs(session_dir, exist_ok=True)
        self.resources = dict(resources or detect_resources())
        self.available = dict(self.resources)
        self._res_lock = threading.Lock()

        store_mem = object_store_memory or CONFIG.object_store_memory_bytes
        self.store_path = os.path.join(
            self._pick_store_dir(store_mem),
            f"ray_tpu_store_{os.getpid()}_{self.node_id.hex()[:12]}")
        self.store = SharedMemoryStore.create_segment(self.store_path,
                                                      store_mem)
        if CONFIG.object_store_prefault and store_mem >= (1 << 30):
            # big segments: move first-touch fault cost off the put path
            self.store.prefault_async()

        # prefork zygote: launched eagerly so its heavy import (the
        # sitecustomize-mandated jax, ~8 s on this host class) overlaps
        # cluster startup; first worker spawn connects to it
        self._zygote_proc: Optional[subprocess.Popen] = None
        self._zygote_conn: Optional[Any] = None
        self._zygote_lock = threading.Lock()
        self._zygote_sock_path = os.path.join(
            session_dir, f"zygote_{self.node_id.hex()[:12]}.sock")
        if CONFIG.worker_prefork:
            try:
                self._start_zygote()
            except Exception as e:
                logger.warning("zygote start failed (%s); workers will "
                               "exec instead", e)

        self._workers: Dict[str, WorkerHandle] = {}       # worker_id hex ->
        self._idle: Dict[str, deque] = {}                 # sched key -> ids
        self._pending_leases: deque = deque()
        # lease_id -> {"need": resources, "pool": bundle pool key or None}
        self._leases: Dict[str, Dict[str, Any]] = {}
        # placement-group bundle pools reserved on this node:
        # "pgid:index" -> remaining resources in the bundle
        self._bundle_pools: Dict[str, Dict[str, float]] = {}
        self._lock = threading.RLock()
        self._stopped = threading.Event()
        # preemption drain (docs/fault_tolerance.md): once set, new
        # leases are refused (redirected to surviving nodes), queued
        # leases are swept, and the drain thread waits out short tasks
        # before evacuating primary object copies to surviving peers
        self._draining = False
        self._drain_reason = ""
        self._drain_deadline = 0.0

        # handlers that only touch in-memory state under short locks (no
        # spawns, no GCS round trips, no disk): dispatched inline on the
        # reader thread by the RPC fast path.  Lease/actor RPCs stay
        # pooled — they block on spawns and dispatch scans.
        # register_worker MUST be fast: lease_worker handlers park pool
        # threads waiting on worker registration, so a registration
        # queued behind a full pool of parked leases would wedge the
        # whole wave until the lease timeout.
        # fetch_object_chunk is fast too: a shm hit is a pin + an enqueued
        # zero-copy reply frame (the spilled/absent path hands itself to
        # the dispatch pool behind a Deferred before doing anything slow),
        # so pipelined pulls are served back-to-back off the reader with
        # their replies coalescing into shared sendmsg batches.
        fast = frozenset({"was_oom_killed", "store_stats", "node_info",
                          "list_workers", "spill_dir", "register_worker",
                          "fetch_object_chunk", "object_pins"})
        self._server = rpc.Server(self._handle, host=host,
                                  on_disconnect=self._conn_closed,
                                  fast_methods=fast)
        self.address = self._server.address

        self.gcs_address = tuple(gcs_address)
        self.labels = dict(labels or {})
        if CONFIG.tpu_slice_name and "slice" not in self.labels:
            # pod-slice identity rides the node labels so placement
            # machinery can treat one slice's hosts as an atomic bundle
            self.labels["slice"] = CONFIG.tpu_slice_name
        self.gcs = GcsClient(gcs_address, push_handler=self._gcs_push,
                             handler=self._handle, connect_retry=True)
        self.gcs.call("register_node", {
            "node_id": self.node_id.hex(),
            "address": list(self.address),
            "store_path": self.store_path,
            "resources": self.resources,
            "labels": self.labels,
        })

        # runtime telemetry: worker-pool gauge polled at flush time, and
        # this raylet's flusher publishing into the GCS KV
        rtm.gauge_callback("ray_tpu_worker_pool_size",
                           "workers registered to this raylet",
                           lambda: len(self._workers))
        rtm.attach(self.gcs.kv_put,
                   ident="raylet-" + self.node_id.hex()[:12])
        # cluster event plane (docs/observability.md): this raylet's
        # lifecycle events (worker spawn/exit, OOM kills, spill traffic)
        # batch to the GCS event table on the recorder's flusher cadence
        self._events_recorder = cev.configure(
            sink=lambda evs: self.gcs.call(
                "report_cluster_events", {"events": evs}, timeout=5),
            source="raylet", node_id=self.node_id.hex())
        # folded stacks sampled just before a hang-timeout kill, keyed
        # by worker id until the dossier harvest consumes them
        self._hang_stacks: Dict[str, Any] = {}

        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True)
        self._hb_thread.start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()
        self._spiller = threading.Thread(target=self._lease_spillback_loop,
                                         daemon=True)
        self._spiller.start()

        # object spilling (reference: LocalObjectManager,
        # src/ray/raylet/local_object_manager.h:41 + external_storage.py:72):
        # when shm usage crosses object_spill_threshold, LRU sealed unpinned
        # objects move to disk files; fetches restore or stream them back.
        self._spill_dir = os.path.join(
            CONFIG.object_store_fallback_dir or session_dir,
            f"spill_{self.node_id.hex()[:12]}")
        os.makedirs(self._spill_dir, exist_ok=True)
        # spill proper goes through the pluggable storage seam (URI-keyed;
        # mock:// + fault wrappers in tests); fallback-allocated primaries
        # written by clients stay plain local files in _spill_dir
        from ray_tpu._private import storage as _storage
        base = CONFIG.object_spill_uri or self._spill_dir
        self._spill_store, self._spill_key_base = _storage.get_storage(base)
        # one fault seam: the legacy object_spill_fault presets and the
        # numeric knobs both wrap the backend in the same FlakyStorage
        fail_rate = CONFIG.object_spill_failure_rate
        slow_ms = CONFIG.object_spill_slow_ms
        if CONFIG.object_spill_fault == "unstable":
            fail_rate = max(fail_rate, 0.5)  # fail every other write
        elif CONFIG.object_spill_fault == "slow":
            slow_ms = max(slow_ms, 500.0)
        if fail_rate or slow_ms:
            self._spill_store = _storage.FlakyStorage(
                self._spill_store, failure_rate=fail_rate, slow_ms=slow_ms)
        self._fs_store = _storage.FileStorage()
        self._fallback_local: set = set()  # oids whose bytes are local files
        # disk-full protection for spill/fallback writes (reference
        # FileSystemMonitor, src/ray/common/file_system_monitor.h)
        from ray_tpu._private.file_system_monitor import FileSystemMonitor
        self._fs_monitor = FileSystemMonitor(
            self._spill_dir,
            on_over=lambda usage: self._report_event(
                "ERROR", "OUT_OF_DISK",
                f"filesystem {usage:.0%} full: spilling disabled",
                usage=round(usage, 3)))
        self._spilled: Dict[bytes, Tuple[int, int]] = {}  # oid -> (size, meta)
        # frees that couldn't complete yet (object pinned, e.g. mid-spill);
        # retried by the spill loop so a free racing a spill can't leak the
        # resulting file or shm copy
        self._deferred_frees: set = set()
        self._restoring: set = set()  # oids mid restore (file -> shm)
        self._spill_mutex = threading.Lock()
        self._obj_spiller = threading.Thread(target=self._object_spill_loop,
                                             daemon=True)
        self._obj_spiller.start()

        # bulk data plane, raylet side (docs/object_transfer.md): pooled
        # peer connections + a pull engine for argument prefetch.  The
        # prefetch budget shares the process-wide cap semantics with
        # client pulls so a wave of lease requests can't overcommit shm.
        self._conn_cache = transfer.ConnCache()
        # (ts, nodes) list_nodes snapshot (_gcs_nodes): one tuple so
        # concurrent lease handlers read it atomically.  Callers pick
        # their own staleness bound — availability is advisory (a
        # locality-redirect target re-checks feasibility and can spill
        # back), and addresses are stabler still.
        self._nodes_snapshot: Tuple[float, list] = (0.0, [])
        self._prefetch_budget = transfer.PullBudget(
            CONFIG.pull_memory_cap_bytes)
        self._puller = transfer.ObjectPuller(
            self.store, self._peer_address, self._conn_cache.get,
            budget=self._prefetch_budget)
        # oid binary -> (pinned view, expires_at): prefetched arguments
        # stay pinned so eviction/spill can't undo the transfer before
        # the task runs; dropped on free, else reaped after
        # prefetch_pin_ttl_s (lease timed out / task cancelled)
        self._prefetch_pins: Dict[bytes, Tuple[memoryview, float]] = {}
        self._prefetch_inflight: set = set()
        # freed while its prefetch was still pulling: the completion must
        # discard the copy instead of pinning a resurrected object
        self._prefetch_freed: set = set()
        # pins taken by evacuation ingest (subset of _prefetch_pins):
        # unlike plain prefetch replicas, these may be an object's LAST
        # copy (cascading drains) and must re-evacuate if THIS node
        # drains too
        self._evac_keep: set = set()
        self._prefetch_lock = threading.Lock()
        # bounded: a lease storm carrying many large-arg entries queues
        # here instead of spawning a thread per argument (PullBudget
        # bounds the bytes, this bounds the threads)
        self._prefetch_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="arg-prefetch")

        # host-memory monitor + OOM worker-killing policy (reference
        # MemoryMonitor, memory_monitor.h:52 + worker_killing_policy.h)
        from ray_tpu._private.memory_monitor import MemoryMonitor
        self._oom_kills: Dict[str, float] = {}   # worker_id -> kill time
        self._oom_kill_count = 0
        self._last_oom_kill = 0.0
        self._memory_monitor = MemoryMonitor(self._on_memory_breach)
        if self._memory_monitor.enabled:
            self._mem_thread = threading.Thread(
                target=self._memory_monitor_loop, daemon=True)
            self._mem_thread.start()
        if CONFIG.log_to_driver:
            from ray_tpu._private.log_monitor import LogMonitor

            def job_of(worker_prefix: str):
                with self._lock:
                    for wid, h in self._workers.items():
                        if wid.startswith(worker_prefix):
                            return h.job_id
                return None

            self._log_monitor = LogMonitor(session_dir, self.gcs,
                                           self.node_id.hex(), job_of)
            self._log_monitor.start()
        else:
            self._log_monitor = None

    def _report_event(self, severity: str, label: str, message: str,
                      **fields) -> None:
        """Typed component event via the batched event plane.  Emission
        sites sit on memory-critical paths (OOM kill, spill under
        _spill_mutex) — emit() is a ring append; the recorder's flusher
        pays the GCS round trip off-path."""
        cev.emit(label, message, severity=severity, **fields)

    # --------------------------------------------------------------- serving
    def _handle(self, conn: rpc.Connection, method: str, p: Any) -> Any:
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"raylet: unknown method {method}")
        return fn(conn, p or {})

    def _gcs_push(self, method: str, payload: Any) -> None:
        if method == "kill_actor_worker":
            self._kill_actor_worker(payload["actor_id"])
        elif method == "pubsub":
            pass

    def _conn_closed(self, conn: rpc.Connection) -> None:
        peer = getattr(conn, "peer", None)
        if isinstance(peer, tuple) and peer and peer[0] == "worker":
            self._on_worker_dead(peer[1], "connection lost")

    # ------------------------------------------------------------- heartbeat
    def _node_health(self, loop_lag_ms: float) -> Dict[str, Any]:
        """Health snapshot piggybacked on heartbeats (cpu/mem/store
        occupancy, heartbeat-loop lag, worker-pool size): feeds the
        GCS NODE_UNHEALTHY threshold and the `ray-tpu status` health
        table (docs/observability.md)."""
        health: Dict[str, Any] = {
            "loop_lag_ms": round(loop_lag_ms, 1),
            "workers": len(self._workers),
            "oom_kills": self._oom_kill_count,
        }
        try:
            vm = psutil.virtual_memory()
            health["mem_frac"] = round(vm.percent / 100.0, 4)
            health["cpu_frac"] = round(
                psutil.cpu_percent(interval=None) / 100.0, 4)
        except Exception:
            pass
        try:
            st = self.store.stats()
            health["store_frac"] = round(
                st["bytes_in_use"] / max(1, st["capacity"]), 4)
        except Exception:
            pass
        return health

    def _heartbeat_loop(self) -> None:
        period = CONFIG.heartbeat_period_ms / 1000.0
        beats = 0
        t_sleep = time.monotonic()
        while not self._stopped.wait(period):
            # loop lag = how late this wake fired vs the period —
            # stamped against the moment we went to SLEEP, so it
            # measures thread starvation (overloaded box) only, not
            # the previous iteration's work (a slow GCS heartbeat RPC
            # must not flip every node to NODE_UNHEALTHY)
            now = time.monotonic()
            loop_lag_ms = max(0.0, (now - t_sleep - period) * 1000.0)
            beats += 1
            try:
                with self._res_lock:
                    avail = dict(self.available)
                with self._lock:
                    # aggregate queued lease demand by resource shape so the
                    # autoscaler can binpack it (reference: resource_load_by_shape
                    # carried in heartbeats to GCS for the monitor)
                    shapes: Dict[tuple, int] = {}
                    for req in self._pending_leases:
                        need = dict(req["resources"])
                        need.setdefault("CPU", 1.0)
                        key = tuple(sorted(need.items()))
                        shapes[key] = shapes.get(key, 0) + 1
                    load = [{"shape": dict(k), "count": c}
                            for k, c in shapes.items()]
                    busy = bool(self._leases) or bool(self._bundle_pools)
                if not busy:
                    # a node whose store (or spill dir) still holds live
                    # objects is not idle: terminating it would strand
                    # ObjectRefs on their primary copies
                    try:
                        busy = (self.store.stats()["num_objects"] > 0
                                or bool(self._spilled))
                    except Exception:
                        busy = True
                hb = {"node_id": self.node_id.hex(),
                      "available": avail,
                      "load": load,
                      "busy": busy}
                with self._res_lock:
                    # bundle-pool reconciliation (docs/fault_tolerance
                    # .md): report the reservations we hold so the GCS
                    # can flag ones it no longer places here (pg
                    # removed / rescheduled while we were unreachable)
                    hb["bundles"] = list(self._bundle_pools)
                if self._draining:
                    hb["draining"] = True
                    hb["drain_reason"] = self._drain_reason
                    hb["drain_grace_s"] = max(
                        0.0, self._drain_deadline - time.monotonic())
                # health snapshot every ~1s (or immediately when the
                # loop itself lagged): cheap, and the GCS only edge-
                # triggers events on threshold crossings
                if beats % max(1, int(round(1.0 / period))) == 0 or \
                        loop_lag_ms >= CONFIG.node_unhealthy_lag_ms:
                    hb["health"] = self._node_health(loop_lag_ms)
                reply = self.gcs.call("heartbeat", hb)
                if reply and reply.get("reregister"):
                    # the GCS restarted without our node in its restored
                    # state: introduce ourselves again
                    try:
                        self.gcs.call("register_node", {
                            "node_id": self.node_id.hex(),
                            "address": list(self.address),
                            "store_path": self.store_path,
                            "resources": self.resources,
                            "labels": self.labels,
                        })
                    except (ConnectionError, rpc.RpcError, TimeoutError):
                        pass
                    continue
                if reply and reply.get("dead"):
                    # the GCS declared us dead and restarted our actors
                    # elsewhere; fate-share instead of running split-brain
                    logger.error("GCS declared this node dead; shutting down")
                    threading.Thread(target=self.shutdown,
                                     daemon=True).start()
                    return
                if reply and reply.get("stale_bundles"):
                    # off-thread: the verify round trip must not delay
                    # liveness reporting past the death threshold
                    threading.Thread(
                        target=self._release_stale_bundles,
                        args=(list(reply["stale_bundles"]),),
                        daemon=True).start()
            except (ConnectionError, rpc.RpcError, TimeoutError):
                if self._stopped.is_set():
                    return
                logger.warning("heartbeat to GCS failed")
            finally:
                # re-stamp at the bottom of every iteration (all exit
                # paths incl. continue) so the next wake's lag excludes
                # this iteration's own work
                t_sleep = time.monotonic()

    def _lease_spillback_loop(self) -> None:
        """Dedicated thread: never blocks heartbeats (a slow GCS list_nodes
        here must not delay liveness reporting past the death threshold)."""
        while not self._stopped.wait(1.0):
            try:
                self._lease_spillback_scan()
            except Exception:
                logger.exception("lease spillback scan failed")

    def _lease_spillback_scan(self) -> None:
        """Redirect stale queued leases to nodes that now have capacity.

        When the autoscaler (ray_tpu/autoscaler/) brings a node up, requests
        queued here before it existed would otherwise sit until their lease
        timeout; this is the queued-side half of the reference's spillback
        (cluster_task_manager spilling queued work on cluster view changes).
        """
        with self._lock:
            stale = [r for r in self._pending_leases
                     if r.get("pool") is None and r.get("spillback", 0) < 2
                     and time.monotonic() - r.get("t_queued", 0) > 1.0]
        if not stale:
            return
        # one cluster snapshot per scan, shared across all stale requests
        try:
            nodes = self.gcs.call("list_nodes", timeout=2)
        except (ConnectionError, rpc.RemoteError, TimeoutError):
            return
        remote_nodes = [n for n in nodes
                        if n["node_id"] != self.node_id.hex()
                        and n["alive"] and not n.get("draining")]
        for req in stale:
            need = dict(req["resources"])
            need.setdefault("CPU", 1.0)
            with self._res_lock:
                local_ok = all(self.available.get(r, 0) >= v
                               for r, v in need.items())
            if local_ok:
                continue
            target = None
            for node in remote_nodes:
                if all(node["available"].get(r, 0) >= v
                       for r, v in need.items()):
                    target = tuple(node["address"])
                    break
            if target is None:
                continue
            with self._lock:
                if req not in self._pending_leases:
                    continue  # granted concurrently
                self._pending_leases.remove(req)
                req["out"]["grant"] = {"retry_at": list(target)}
                req["event"].set()

    # --------------------------------------------------------- object spill
    def _object_spill_loop(self) -> None:
        while not self._stopped.wait(0.2):
            try:
                self._reap_prefetch_pins()
                self._retry_deferred_frees()
                self._object_spill_scan()
            except Exception:
                logger.exception("object spill scan failed")

    def _object_spill_scan(self) -> int:
        """High-water spill: keep shm usage below object_spill_threshold by
        moving LRU sealed unpinned objects to disk (hysteresis: spill down
        to 90% of the threshold so the loop doesn't thrash at the line)."""
        st = self.store.stats()
        hi = CONFIG.object_spill_threshold * st["capacity"]
        if st["bytes_in_use"] <= hi:
            return 0
        return self._spill_bytes(st["bytes_in_use"] - int(hi * 0.9))

    def _spill_path(self, oid) -> str:
        return os.path.join(self._spill_dir, oid.hex())

    def _spill_loc(self, oid):
        """-> (storage, key) holding this object's spilled bytes."""
        with self._lock:
            fb = oid.binary() in self._fallback_local
        if fb:
            return self._fs_store, self._spill_path(oid)
        return self._spill_store, f"{self._spill_key_base}/{oid.hex()}"

    def _spill_bytes(self, needed: int) -> int:
        """Spill LRU-first until ``needed`` bytes left shm (or no victims)."""
        with self._spill_mutex:
            objs = [o for o in self.store.list_objects() if o[3] == 0]
            objs.sort(key=lambda t: t[2])  # oldest lru_tick first
            freed = 0
            for oid, size, _tick, _pins in objs:
                if freed >= needed:
                    break
                if self._spill_one(oid, size):
                    freed += size
            return freed

    def _spill_one(self, oid, size: int) -> bool:
        if not CONFIG.object_spill_uri and self._fs_monitor.over_capacity():
            return False  # disk full: keep the shm copy, fail gracefully
        with self._lock:
            if oid.binary() in self._deferred_frees:
                return False  # being freed: spilling it would leak the file
        res = self.store.get(oid, timeout=0.0)
        if res is None:
            return False
        buf, meta = res
        sstore, skey = self._spill_loc(oid)
        try:
            # pass the shm memoryview straight through: FileStorage
            # streams it to disk without a heap copy (spilling fires
            # exactly when memory is tight)
            try:
                sstore.write_bytes(skey, buf)
            except OSError as e:
                # flaky/full spill target: keep the shm copy, the next
                # scan retries (reference spill IO error path)
                logger.warning("spill write of %s failed: %s",
                               oid.hex()[:12], e)
                self._report_event("WARNING", "SPILL_WRITE_FAILED",
                                   f"spill of {oid.hex()[:12]} failed: {e}")
                return False
        finally:
            buf.release()
            self.store.release(oid)
        # record before delete: a fetch racing the handoff finds the object
        # in at least one of the two places (both is harmless — immutable)
        with self._lock:
            self._spilled[oid.binary()] = (size, meta)
        if not self.store.delete(oid):
            # pinned between release and delete: keep it in shm
            with self._lock:
                self._spilled.pop(oid.binary(), None)
            sstore.delete(skey)
            return False
        logger.debug("spilled %s (%d bytes)", oid.hex()[:12], size)
        cev.emit(cev.OBJECT_SPILL, f"spilled {oid.hex()[:12]}",
                 severity="DEBUG", object_id=oid.hex(), bytes=size)
        return True

    def _fetch_spilled_chunk(self, oid, p):
        """Serve a chunk of a spilled object as (value, on_sent), racing
        safely against a concurrent restore (which removes the file and
        re-creates the shm copy): a None value is authoritative 'absent'
        to owners, so every transient mid-handoff window must be retried,
        never reported — and an exhausted run of flaky storage reads
        raises (the owner maps a transport error to 'transient', never to
        lost)."""
        io_error = None
        for _ in range(3):
            with self._lock:
                rec = self._spilled.get(oid.binary())
            if rec is None:
                # not spilled (anymore): a concurrent restore may have just
                # moved it to shm — block only if one is actually in flight
                # (a plain absent object must answer fast: owners treat it
                # as authoritative for reconstruction)
                with self._lock:
                    restoring = oid.binary() in self._restoring
                res = self.store.get(oid, timeout=2.0 if restoring else 0.0)
                if res is None:
                    return None, None
                return self._chunk_reply(oid, res, p)
            size, meta = rec
            # restore into shm when it fits under the spill threshold
            # (reference LocalObjectManager restore / plasma re-create
            # path) so subsequent local gets are zero-copy again
            st = self.store.stats()
            if st["bytes_in_use"] + size <= \
                    CONFIG.object_spill_threshold * st["capacity"]:
                if self._restore_one(oid, size, meta):
                    # blocking get: a concurrent restorer may not have
                    # sealed yet
                    res = self.store.get(oid, timeout=2.0)
                    if res is not None:
                        return self._chunk_reply(oid, res, p)
                    # "restored concurrently" may actually be a remote
                    # pull's UNSEALED destination create for this very
                    # object (it will seal only after we answer) — fall
                    # through and serve from the spill file, which that
                    # restore-miss left intact.  A true concurrent
                    # restore deleted the file: FileNotFoundError below
                    # re-resolves, keeping the old retry behavior.
            sstore, skey = self._spill_loc(oid)
            try:
                data = sstore.read_bytes(skey, int(p.get("offset", 0)),
                                         int(p.get("length", size)))
                return {"total": size, "meta": meta, "data": data}, None
            except FileNotFoundError:
                continue  # restored (or freed) under us: re-resolve
            except OSError as e:
                io_error = e
                continue  # flaky storage read: retry
        if io_error is not None:
            raise rpc.RpcError(
                f"spill storage read failed for {oid.hex()[:12]}: "
                f"{io_error}")
        return None, None

    def _restore_one(self, oid, size: int, meta: int) -> bool:
        from ray_tpu.exceptions import ObjectStoreFullError
        # Mark restoring BEFORE reading the file: _rpc_free_objects checks
        # _restoring under the same lock, so either it sees us and defers
        # the free (retried until the copy stays gone) or it unlinks first
        # and our read fails — no window where a freed object is re-sealed
        # into shm untracked.
        with self._lock:
            self._restoring.add(oid.binary())
        try:
            sstore, skey = self._spill_loc(oid)
            try:
                data = sstore.read_bytes(skey)
            except FileNotFoundError:
                return False
            except OSError:
                return False  # flaky storage read: fetch path retries
            try:
                buf = self.store.create(oid, size, meta=meta,
                                        allow_evict=False)
            except FileExistsError:
                return True  # restored concurrently
            except (ObjectStoreFullError, OSError):
                return False
            try:
                buf[:len(data)] = data
            finally:
                buf.release()
            self.store.seal(oid)
            with self._lock:
                self._spilled.pop(oid.binary(), None)
                self._fallback_local.discard(oid.binary())
            sstore.delete(skey)
            logger.debug("restored %s (%d bytes)", oid.hex()[:12], size)
            cev.emit(cev.OBJECT_RESTORE, f"restored {oid.hex()[:12]}",
                     severity="DEBUG", object_id=oid.hex(), bytes=size)
            return True
        finally:
            with self._lock:
                self._restoring.discard(oid.binary())

    def _rpc_profile(self, conn, p):
        """Flame-sample this raylet, or forward to one of its workers
        (reference reporter_agent on-demand CPU profiling)."""
        wid = p.get("worker_id")
        duration = float(p.get("duration", 2.0))
        if wid:
            with self._lock:
                h = None
                for w, handle in self._workers.items():
                    if w.startswith(wid):
                        h = handle
                        break
            if h is None or h.conn is None:
                raise rpc.RpcError(f"no live worker matching {wid!r}")
            fwd = {"duration": duration}
            if "device" in p:   # gang/device capture passes through
                fwd["device"] = bool(p.get("device"))
            return h.conn.call("profile", fwd, timeout=duration + 30)
        from ray_tpu._private.profiler import sample_folded
        return sample_folded(duration)

    def _rpc_dump_stacks(self, conn, p):
        """Instant per-thread stacks + a short folded sample of this
        raylet — or, with ``worker_id``/``pid``, forwarded to one of
        its workers (`ray-tpu summary stacks`, docs/observability.md:
        sampling a stalled process without gdb)."""
        wid = p.get("worker_id")
        pid = p.get("pid")
        if wid or pid:
            with self._lock:
                h = None
                for w, handle in self._workers.items():
                    if (wid and w.startswith(wid)) or \
                            (pid and handle.proc.pid == int(pid)):
                        h = handle
                        break
            if h is None or h.conn is None:
                raise rpc.RpcError(
                    f"no live worker matching {wid or pid!r}")
            return h.conn.call("dump_stacks",
                               {"duration": p.get("duration", 0.2)},
                               timeout=30)
        from ray_tpu._private.profiler import dump_stacks, sample_folded
        return {"threads": dump_stacks(),
                "folded": sample_folded(float(p.get("duration", 0.2)))}

    def _rpc_spill_dir(self, conn, p):
        """Clients writing fallback-allocated primaries need the dir."""
        if self._fs_monitor.over_capacity():
            raise rpc.RpcError(
                "out of disk: local filesystem is above "
                f"{CONFIG.local_fs_capacity_threshold:.0%} capacity; "
                "fallback allocation refused")
        return self._spill_dir

    def _rpc_register_spilled(self, conn, p):
        """A client wrote a primary copy straight to the spill dir (plasma
        fallback-allocation analog); track it like any spilled object."""
        from ray_tpu._private.ids import ObjectID
        oid = ObjectID(p["object_id"])
        with self._lock:
            self._fallback_local.add(oid.binary())
            self._spilled[oid.binary()] = (int(p["size"]),
                                           int(p.get("meta", 0)))
        return {"ok": True}

    def _rpc_request_spill(self, conn, p):
        """A client's create failed for lack of space: spill at least
        ``bytes`` synchronously so its retry can succeed."""
        freed = self._spill_bytes(int(p.get("bytes", 0)) or 1)
        return {"freed": freed}

    def _rpc_free_objects(self, conn, p):
        """Owner says these objects' refcounts hit zero: drop the primary
        copies (shm + spill files) on this node."""
        from ray_tpu._private.ids import ObjectID
        for ob in p.get("object_ids", ()):
            oid = ObjectID(ob)
            # a prefetch pin must never turn a free into a deferred retry
            # loop: drop ours first, then delete.  An in-flight prefetch
            # gets a tombstone so its completion discards the copy
            # instead of resurrecting a freed object under a 60 s pin.
            with self._prefetch_lock:
                if bytes(ob) in self._prefetch_inflight:
                    self._prefetch_freed.add(bytes(ob))
            self._release_prefetch_pin(bytes(ob))
            deleted = self.store.delete(oid)
            sstore, skey = self._spill_loc(oid)
            with self._lock:
                rec = self._spilled.pop(oid.binary(), None)
                self._fallback_local.discard(oid.binary())
                restoring = oid.binary() in self._restoring
            if rec is not None:
                sstore.delete(skey)
            if restoring:
                # a concurrent _restore_one may re-seal this object into
                # shm after our delete; defer so the retry loop deletes
                # whatever copy the restore produces
                with self._lock:
                    self._deferred_frees.add(oid.binary())
            elif not deleted and self.store.contains(oid):
                # pinned right now (a reader, or _spill_one mid-handoff):
                # the single free RPC must still win eventually
                with self._lock:
                    self._deferred_frees.add(oid.binary())
        return {"ok": True}

    def _retry_deferred_frees(self) -> None:
        from ray_tpu._private.ids import ObjectID
        with self._lock:
            pending = list(self._deferred_frees)
        for ob in pending:
            oid = ObjectID(ob)
            self.store.delete(oid)
            sstore, skey = self._spill_loc(oid)
            with self._lock:
                rec = self._spilled.pop(ob, None)
                self._fallback_local.discard(ob)
            if rec is not None:
                sstore.delete(skey)
            with self._lock:
                # keep the entry while a restore is in flight: contains()
                # is momentarily False while _restore_one reads the spill
                # file, and dropping the free here would let the restore
                # seal a zero-refcount object into shm permanently
                if not self.store.contains(oid) \
                        and ob not in self._restoring:
                    self._deferred_frees.discard(ob)

    # --------------------------------------------------------- memory / OOM
    def _memory_monitor_loop(self) -> None:
        while not self._stopped.wait(self._memory_monitor.refresh_s):
            try:
                self._memory_monitor.poll_once()
            except Exception:
                logger.exception("memory monitor poll failed")

    def _on_memory_breach(self, usage: float) -> None:
        """Kill one worker per refresh period at most — killing frees
        memory asynchronously, so firing every poll would massacre the
        pool before the first kill lands."""
        now = time.monotonic()
        if now - self._last_oom_kill < self._memory_monitor.refresh_s * 2:
            return
        from ray_tpu._private.memory_monitor import pick_oom_victim
        with self._lock:
            view = [(wid, h.actor_id is not None, h.started_at,
                     h.lease_id is not None)
                    for wid, h in self._workers.items()]
        victim = pick_oom_victim(view)
        if victim is None:
            logger.warning("memory usage %.2f over threshold but no "
                           "killable worker", usage)
            return
        with self._lock:
            # re-check under the lock: a victim that exited on its own
            # since the snapshot must not be charged as an OOM kill (its
            # owner would silently retry a crash on the OOM budget)
            if victim not in self._workers:
                return
            self._last_oom_kill = now
            self._oom_kills[victim] = now
            self._oom_kill_count += 1
            # bound the ledger; owners query within seconds of the kill
            if len(self._oom_kills) > 1024:
                for k in sorted(self._oom_kills,
                                key=self._oom_kills.get)[:512]:
                    del self._oom_kills[k]
        logger.warning("memory usage %.2f >= %.2f: OOM-killing worker %s "
                       "(retriable-LIFO policy)", usage,
                       self._memory_monitor.threshold, victim[:8])
        self._report_event("ERROR", "OOM_KILL",
                           f"host memory {usage:.0%}: killed worker "
                           f"{victim[:8]}", worker_id=victim,
                           usage=round(usage, 3))
        self._kill_worker(victim, f"OOM-killed (host memory {usage:.0%})",
                          force=True)

    def _rpc_die(self, conn, p):
        """Chaos seam (reference NodeKiller, _private/test_utils.py:1301):
        hard-exit the raylet as if the node vanished.  Workers fate-share
        via their raylet connection; graceful=False skips all cleanup."""
        logger.warning("raylet received die request (chaos)")

        def _exit():
            time.sleep(0.05)  # let the RPC reply flush
            os._exit(1)

        threading.Thread(target=_exit, daemon=True).start()
        return {"ok": True}

    # --------------------------------------------------- preemption drain
    def _rpc_drain(self, conn, p):
        """Graceful-preemption drain (spot notice, `ray-tpu drain`):
        emit NODE_PREEMPTING with the grace deadline, stop granting
        leases, let short tasks finish, then evacuate primary object
        copies to surviving nodes over the transfer plane
        (docs/fault_tolerance.md).  Idempotent."""
        raw_grace = p.get("grace_s")
        # explicit 0 means "die ASAP, evacuate now" — `or` would turn
        # it into the 30s default
        grace = CONFIG.drain_grace_s if raw_grace is None \
            else float(raw_grace)
        reason = p.get("reason", "drain requested")
        with self._lock:
            already = self._draining
            self._draining = True
            self._drain_reason = reason
            new_deadline = time.monotonic() + grace
            if already:
                # a later notice can only SHORTEN the window (a 300s
                # maintenance drain followed by a 5s spot notice must
                # evacuate now); the running drain loop re-reads the
                # deadline every tick
                self._drain_deadline = min(self._drain_deadline,
                                           new_deadline)
            else:
                self._drain_deadline = new_deadline
        if already:
            return {"ok": True, "already": True}
        logger.warning("draining: %s (grace %.0fs)", reason, grace)
        # ring_only: the GCS emits the one canonical NODE_PREEMPTING
        # table event (either RPC path reports there); this copy is a
        # flight-ring breadcrumb for this raylet's dossier
        cev.emit(cev.NODE_PREEMPTING,
                 f"raylet draining: {reason} (grace {grace:.0f}s)",
                 severity="WARNING", ring_only=True,
                 grace_s=grace, reason=reason)
        if not p.get("from_gcs"):
            # direct raylet-RPC drain: reflect it in the GCS node table
            # so placement stops choosing this node immediately (the
            # heartbeat-carried flag is the idempotent backstop)
            try:
                self.gcs.call("report_node_draining",
                              {"node_id": self.node_id.hex(),
                               "grace_s": grace, "reason": reason},
                              timeout=5)
            except (ConnectionError, rpc.RpcError, TimeoutError):
                pass
        threading.Thread(target=self._drain_loop, args=(grace, reason),
                         daemon=True).start()
        return {"ok": True}

    def _drain_loop(self, grace: float, reason: str) -> None:
        """Runs the drain to completion: sweep queued leases, wait out
        in-flight task leases (actors are restarted elsewhere by the
        GCS when the node dies — their leases never drain), evacuate,
        report the ledger.  Best effort end to end: a drain must never
        crash the raylet it is trying to wind down."""
        t0 = time.monotonic()
        try:
            self._sweep_queued_leases()
            # live deadline read: a later, shorter preemption notice
            # shrinks _drain_deadline and this wait must honor it.  The
            # lease wait RESERVES part of the window for evacuation — a
            # task that outlives the grace must not eat the whole
            # budget and leave the primary copies to die with the node.
            evac_reserve = min(10.0, 0.4 * grace)
            while time.monotonic() < self._drain_deadline - evac_reserve:
                with self._lock:
                    busy = [lid for lid in self._leases
                            if not lid.startswith("actor-")]
                if not busy:
                    break
                time.sleep(0.2)
            evacuated = nbytes = failed = 0
            if CONFIG.evacuation_enabled:
                evacuated, nbytes, failed = self._evacuate_objects(
                    self._drain_deadline)
            try:
                self.gcs.call("report_node_drained",
                              {"node_id": self.node_id.hex(),
                               "evacuated": evacuated, "bytes": nbytes,
                               "failed": failed,
                               "duration_s": round(
                                   time.monotonic() - t0, 3)},
                              timeout=10)
            except (ConnectionError, rpc.RpcError, TimeoutError):
                pass
            logger.warning("drain complete: %d objects evacuated "
                           "(%d bytes, %d failed) in %.1fs", evacuated,
                           nbytes, failed, time.monotonic() - t0)
        except Exception:
            logger.exception("drain loop failed")

    def _sweep_queued_leases(self) -> None:
        """Resolve every queued lease request with a redirect to a
        surviving node (or a clean error): a request parked behind this
        node's resources must not sit until its timeout while the node
        is going away.  Redirect rules mirror the lease handler: only
        non-bundle, spillback<2 requests can follow a retry_at — the
        other shapes consume the reply as a final grant."""
        with self._lock:
            stranded = list(self._pending_leases)
            self._pending_leases.clear()
        if not stranded:
            return
        # one cluster snapshot for the whole sweep (the stale-request
        # scan above does the same): N queued leases must not cost N
        # list_nodes round trips on the drain path
        try:
            nodes = self.gcs.call("list_nodes", timeout=5)
        except (ConnectionError, rpc.RemoteError, TimeoutError):
            nodes = []
        candidates = [n for n in nodes
                      if n["node_id"] != self.node_id.hex()
                      and n["alive"] and not n.get("draining")]
        for req in stranded:
            need = dict(req["resources"])
            need.setdefault("CPU", 1.0)
            target = None
            if req.get("pool") is None and req.get("spillback", 0) < 2:
                for node in candidates:
                    if all(node["available"].get(r, 0) >= v
                           for r, v in need.items()):
                        target = tuple(node["address"])
                        break
            if target is not None:
                req["out"]["grant"] = {"retry_at": list(target)}
            else:
                req["out"]["error"] = "node draining (preemption " \
                                      "imminent); no alternative node"
            req["event"].set()

    def _evacuation_targets(self) -> list:
        return [n for n in self._gcs_nodes(0.5)
                if n.get("alive") and not n.get("draining")
                and n["node_id"] != self.node_id.hex()]

    def _evacuate_objects(self, deadline: float) -> Tuple[int, int, int]:
        """Ship every local primary copy (sealed shm objects + spilled
        files) to surviving nodes: the receiving raylet pulls over the
        transfer plane (`ingest_object`), pins the copy for
        evac_pin_ttl_s, and the landing is registered in the GCS
        evacuated-object table so owners find it the moment their old
        location set dies (docs/fault_tolerance.md).  -> (evacuated,
        bytes, failed)."""
        targets = self._evacuation_targets()
        if not targets:
            # distinct label: the canonical NODE_DRAINED (with its
            # ledger) still comes from the GCS at drain completion
            self._report_event("ERROR", "EVACUATION_SKIPPED",
                               "evacuation skipped: no surviving node")
            return 0, 0, 0
        with self._prefetch_lock:
            # plain prefetch pins are borrowed REPLICAS of arguments
            # whose primaries live elsewhere — shipping them would
            # burn the grace window on copies nobody will miss.
            # Evac-ingested pins stay: after a cascading drain they
            # may be an object's last copy.
            skip = set(self._prefetch_pins) - self._evac_keep
        work = []   # (oid, size)
        for oid, size, _tick, _pins in self.store.list_objects():
            if oid.binary() not in skip:
                work.append((oid, size))
        from ray_tpu._private.ids import ObjectID
        with self._lock:
            shm = {o.binary() for o, _s in work}
            for ob, (size, _meta) in self._spilled.items():
                if ob not in shm and ob not in skip:
                    work.append((ObjectID(ob), size))
        if not work:
            return 0, 0, 0
        results = []
        with ThreadPoolExecutor(max_workers=4,
                                thread_name_prefix="evac") as pool:
            # rotated target list per object: the primary target is
            # round-robin, but a refusal (full store, transfer already
            # in flight, transient unreachability) falls over to the
            # remaining survivors instead of abandoning the object
            futs = [pool.submit(
                        self._evacuate_one, oid, size,
                        targets[i % len(targets):] +
                        targets[:i % len(targets)], deadline)
                    for i, (oid, size) in enumerate(work)]
            for f in futs:
                try:
                    results.append(f.result())
                except Exception:
                    results.append(None)
        evacuated = sum(1 for r in results if r is not None)
        nbytes = sum(r for r in results if r is not None)
        return evacuated, nbytes, len(results) - evacuated

    def _evacuate_one(self, oid, size: int, targets: list,
                      deadline: float) -> Optional[int]:
        """Hand one object to the first of ``targets`` that takes it
        (each raylet pulls it from us); returns the evacuated byte
        count (0 is a legitimate success — empty objects evacuate too),
        None when every target failed."""
        with self._lock:
            if oid.binary() in self._deferred_frees:
                return 0    # being freed: nothing to preserve (success)
        landed = None
        for target in targets:
            if time.monotonic() > deadline + 30.0:
                # far past the grace window: stop churning so the
                # NODE_DRAINED report (which operators wait on) isn't
                # delayed by minutes on a large store
                return None
            timeout = max(2.0, deadline - time.monotonic() + 10.0)
            try:
                conn = self._conn_cache.get(tuple(target["address"]))
                reply = conn.call("ingest_object",
                                  {"object_id": oid.binary(),
                                   "source": self.node_id.hex(),
                                   "timeout": timeout},
                                  timeout=timeout + 5.0)
            except (ConnectionError, rpc.RpcError, TimeoutError,
                    OSError) as e:
                logger.warning("evacuation of %s to %s failed: %s",
                               oid.hex()[:12], target["node_id"][:8], e)
                continue
            if reply and reply.get("ok"):
                landed = target
                break
        if landed is None:
            return None
        try:
            self.gcs.call("report_object_evacuated",
                          {"object_id": oid.hex(),
                           "node_id": landed["node_id"]}, timeout=5)
        except (ConnectionError, rpc.RpcError, TimeoutError):
            return None  # unregistered copy is invisible: don't count it
        cev.emit(cev.OBJECT_EVACUATED,
                 f"evacuated {oid.hex()[:12]} -> "
                 f"{landed['node_id'][:8]}", severity="DEBUG",
                 object_id=oid.hex(), bytes=size,
                 target_node_id=landed["node_id"])
        return size

    def _rpc_ingest_object(self, conn, p):
        """Receiving side of evacuation: pull ``object_id`` from the
        draining ``source`` node over the transfer plane, publish it
        into local shm and pin it for evac_pin_ttl_s (released early by
        the owner's free, like a prefetch pin).  Runs pooled — the pull
        blocks on the network."""
        from ray_tpu._private.ids import ObjectID
        ob = bytes(p["object_id"])
        oid = ObjectID(ob)
        if self._draining:
            raise rpc.RpcError("node draining: refusing evacuation")
        with self._lock:
            if ob in self._spilled:
                return {"ok": True, "already": True}
        if self.store.contains(oid):
            return {"ok": True, "already": True}
        with self._prefetch_lock:
            if ob in self._prefetch_inflight:
                # a prefetch is mid-pull for the same object: it will
                # land a local copy anyway — report not-ours so the
                # drainer tries another target for durability
                return {"ok": False, "reason": "transfer in flight"}
            self._prefetch_inflight.add(ob)
        try:
            out = self._puller.pull(
                oid, [p["source"]],
                deadline=time.monotonic() + float(p.get("timeout", 30.0)),
                publish_small=True)
            if out.status != "ok" or not out.published:
                return {"ok": False, "reason": out.status}
            with self._prefetch_lock:
                freed = ob in self._prefetch_freed
                if not freed:
                    self._prefetch_pins[ob] = (
                        out.data,
                        time.monotonic() + CONFIG.evac_pin_ttl_s)
                    self._evac_keep.add(ob)
            if freed:
                # freed while we pulled: discard instead of resurrecting
                out.data.release()
                self.store.release(oid)
                self.store.delete(oid)
                return {"ok": False, "reason": "freed during transfer"}
            return {"ok": True, "bytes": out.bytes}
        finally:
            with self._prefetch_lock:
                self._prefetch_inflight.discard(ob)
                self._prefetch_freed.discard(ob)

    def _rpc_was_oom_killed(self, conn, p):
        """Owners distinguish an OOM kill from a plain crash so the
        OOM-specific retry counter applies (reference task_oom_retries)."""
        with self._lock:
            return {"oom": p.get("worker_id") in self._oom_kills}

    def _reap_loop(self) -> None:
        """Detect dead worker processes (cf. WorkerPool child monitoring).
        The loop must survive anything dispatch raises downstream — a
        dead reaper means dead workers are never detected again."""
        while not self._stopped.wait(0.1):
            try:
                with self._lock:
                    handles = list(self._workers.values())
                for h in handles:
                    if h.proc.poll() is not None:
                        self._on_worker_dead(
                            h.worker_id.hex(),
                            f"exit code {h.proc.returncode}")
                self._trim_idle_workers()
            except Exception:
                logger.exception("worker reap pass failed")

    def _trim_idle_workers(self) -> None:
        max_idle = CONFIG.worker_pool_max_idle
        with self._lock:
            idle_ids = [wid for q in self._idle.values() for wid in q]
            excess = len(idle_ids) - max_idle
            victims = []
            if excess > 0:
                now = time.monotonic()
                for wid in idle_ids:
                    h = self._workers.get(wid)
                    if h and now - h.last_idle > 5.0:
                        victims.append(wid)
                        excess -= 1
                        if excess <= 0:
                            break
        for wid in victims:
            self._kill_worker(wid, "idle trim")

    # ------------------------------------------------------------ worker pool
    def _spawn_worker(self, job_id: Optional[str],
                      env_overrides: Optional[Dict[str, str]] = None,
                      language: Optional[str] = None) -> WorkerHandle:
        _M_SPAWNS.inc()
        worker_id = WorkerID.from_random()
        if language == "cpp":
            return self._spawn_cpp_worker(worker_id, job_id, env_overrides)
        if language not in (None, "", "python"):
            raise ValueError(f"unsupported worker language {language!r}")
        from ray_tpu.runtime.node import package_pythonpath
        env = dict(os.environ)
        env.update(env_overrides or {})
        # system-critical keys win over runtime_env env_vars: the child must
        # always be able to import ray_tpu and see the config blob; a user
        # PYTHONPATH is appended, not substituted
        user_pp = (env_overrides or {}).get("PYTHONPATH")
        env["RAY_TPU_SYSTEM_CONFIG"] = CONFIG.overrides_env_blob()
        env["PYTHONPATH"] = package_pythonpath() + (
            os.pathsep + user_pp if user_pp else "")
        # a pip runtime env swaps the interpreter for its venv's python
        # (reference PipProcessor + exec hook): isolation is real — the
        # worker process itself runs inside the env, and the venv's
        # site-packages goes FIRST on PYTHONPATH so pinned versions beat
        # any same-named packages living next to ray_tpu
        python = sys.executable
        container = None
        renv_json = (env_overrides or {}).get("RAY_TPU_RUNTIME_ENV")
        if renv_json:
            import json as _json
            renv = _json.loads(renv_json)
            container = renv.get("container")
            pip_reqs = renv.get("pip")
            if pip_reqs:
                from ray_tpu.runtime_env.pip import (ensure_pip_env,
                                                     venv_site_packages)
                python = ensure_pip_env(pip_reqs)
                env["PYTHONPATH"] = venv_site_packages(python) + \
                    os.pathsep + env["PYTHONPATH"]
        log_prefix = os.path.join(self.session_dir, "logs",
                                  f"worker-{worker_id.hex()[:12]}")
        os.makedirs(os.path.dirname(log_prefix), exist_ok=True)
        cmd = [python, "-m", "ray_tpu.runtime.worker_main",
               "--raylet-host", self.address[0],
               "--raylet-port", str(self.address[1]),
               "--worker-id", worker_id.hex(),
               "--store-path", self.store_path,
               "--session-dir", self.session_dir,
               "--gcs-host", self.gcs_address[0],
               "--gcs-port", str(self.gcs_address[1]),
               "--node-id", self.node_id.hex()]
        if container:
            # containerized workers exec inside the image (cannot fork
            # off the host zygote); the builder raises a clean error
            # when no container runtime exists on this host
            from ray_tpu.runtime_env.container import wrap_worker_command
            cmd = wrap_worker_command(container, cmd,
                                      session_dir=self.session_dir,
                                      store_path=self.store_path,
                                      env=env)
        # the handle is registered BEFORE the process exists: a zygote-
        # forked child starts running instantly and can win the race to
        # register_worker against this (possibly starved) thread — a
        # missing handle there rejects the registration and the newborn
        # worker dies (observed at the 1k-actor burst: one lost worker
        # per ~50-wave wedged its whole create wave)
        handle = WorkerHandle(worker_id, None)
        handle.job_id = job_id
        with self._lock:
            self._workers[worker_id.hex()] = handle
        proc = None
        if CONFIG.worker_prefork and container is None and \
                python == sys.executable and \
                not _env_needs_exec(env_overrides):
            # stock interpreter, no import-time-sensitive env overrides:
            # fork off the warm zygote (ms) instead of exec+reimport
            # (~8 s under the jax sitecustomize).  Venv workers need
            # their own interpreter -> exec path below.
            try:
                proc = self._zygote_spawn(
                    ["worker_main"] + cmd[3:], env,
                    log_prefix + ".out", log_prefix + ".err")
            except Exception as e:
                logger.warning("zygote spawn failed (%s); exec fallback",
                               e)
                # ambiguous outcome: the zygote may still complete the
                # fork after our timeout.  A fresh worker id keeps that
                # orphan from colliding with the exec'd worker (its
                # registration for the old id is simply rejected).
                with self._lock:
                    self._workers.pop(worker_id.hex(), None)
                worker_id = WorkerID.from_random()
                cmd[cmd.index("--worker-id") + 1] = worker_id.hex()
                handle = WorkerHandle(worker_id, None)
                handle.job_id = job_id
                with self._lock:
                    self._workers[worker_id.hex()] = handle
        if proc is None:
            out_f = err_f = None
            try:
                out_f = open(log_prefix + ".out", "ab")
                err_f = open(log_prefix + ".err", "ab")
                proc = subprocess.Popen(cmd, env=env, stdout=out_f,
                                        stderr=err_f, cwd=os.getcwd())
            except Exception:
                # any failure (incl. EMFILE on the opens) must unregister
                # the pending handle or it ghosts in _workers forever
                with self._lock:
                    self._workers.pop(worker_id.hex(), None)
                raise
            finally:
                for f in (out_f, err_f):   # the child holds its own dups
                    if f is not None:
                        f.close()
        # the pending->real swap and the terminated-flag check happen
        # under the SAME lock _kill_worker signals under: without it, a
        # kill could read the placeholder, lose the race to this swap
        # (which then reads terminated=False), and mark an orphaned
        # placeholder — leaking a live worker (TOCTOU)
        with self._lock:
            pending = handle.proc
            handle.proc = proc
            terminated = getattr(pending, "terminated", False)
        if terminated:
            # a kill landed while the process was still being spawned:
            # apply it now instead of leaking a live worker
            try:
                proc.terminate()
            except OSError:
                pass
        cev.emit(cev.WORKER_SPAWN,
                 f"worker {worker_id.hex()[:8]} spawned",
                 worker_id=worker_id.hex(), job_id=job_id,
                 proc_pid=proc.pid)
        return handle

    # ---------------------------------------------------------- zygote
    def _start_zygote(self) -> None:
        from ray_tpu.runtime.node import package_pythonpath
        env = dict(os.environ)
        env["RAY_TPU_SYSTEM_CONFIG"] = CONFIG.overrides_env_blob()
        env["PYTHONPATH"] = package_pythonpath()
        log_prefix = os.path.join(self.session_dir, "logs",
                                  f"zygote-{self.node_id.hex()[:12]}")
        os.makedirs(os.path.dirname(log_prefix), exist_ok=True)
        out_f = open(log_prefix + ".out", "ab")
        err_f = open(log_prefix + ".err", "ab")
        try:
            self._zygote_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.runtime.worker_zygote",
                 "--socket", self._zygote_sock_path],
                env=env, stdout=out_f, stderr=err_f, cwd=os.getcwd())
        finally:
            out_f.close()
            err_f.close()

    def _zygote_spawn(self, argv, env, out_path, err_path) -> ForkedProc:
        """Fork a worker off the warm zygote; raises on any failure (the
        caller execs instead)."""
        import socket as socketlib

        from ray_tpu.runtime import worker_zygote as wz
        with self._zygote_lock:
            if self._zygote_proc is None or \
                    self._zygote_proc.poll() is not None:
                self._zygote_conn = None
                self._start_zygote()
            if self._zygote_conn is None:
                deadline = time.monotonic() + \
                    CONFIG.worker_start_timeout_s * 2
                while True:
                    try:
                        s = socketlib.socket(socketlib.AF_UNIX,
                                             socketlib.SOCK_STREAM)
                        s.connect(self._zygote_sock_path)
                        self._zygote_conn = s
                        break
                    except OSError:
                        s.close()
                        if self._zygote_proc.poll() is not None:
                            raise RuntimeError("zygote exited "
                                               f"{self._zygote_proc.returncode}")
                        if time.monotonic() > deadline:
                            raise TimeoutError("zygote not ready")
                        time.sleep(0.1)
            conn = self._zygote_conn
            try:
                wz.send_msg(conn, {"argv": argv, "env": env,
                                   "stdout": out_path, "stderr": err_path,
                                   "cwd": os.getcwd()})
                # A slow reply is NOT a dead zygote: under a mass-create
                # burst on a starved core the single-threaded zygote can
                # queue spawns for a long time, and a premature timeout
                # here cascades badly — the exec fallback pays a full
                # interpreter+jax import AND the orphaned fork later
                # registers under the superseded id.  So wait on
                # readability in ticks, timing out only on zygote DEATH
                # or a hard deadline far beyond the start timeout.
                import select
                deadline = time.monotonic() + \
                    CONFIG.worker_start_timeout_s * 4
                while True:
                    r, _, _ = select.select([conn], [], [], 1.0)
                    if r:
                        # readable: the reply frame is tiny, but a torn
                        # write from a dying zygote must not block this
                        # thread (it holds _zygote_lock) forever
                        conn.settimeout(CONFIG.worker_start_timeout_s)
                        try:
                            reply = wz.recv_msg(conn)
                        finally:
                            conn.settimeout(None)
                        break
                    if self._zygote_proc.poll() is not None:
                        raise OSError("zygote died "
                                      f"{self._zygote_proc.returncode}")
                    if time.monotonic() > deadline:
                        raise OSError("zygote reply deadline exceeded")
            except OSError as e:
                try:
                    conn.close()
                finally:
                    self._zygote_conn = None
                raise RuntimeError(f"zygote connection failed: {e}")
            if not reply or "pid" not in reply:
                self._zygote_conn = None
                raise RuntimeError("zygote gave no pid")
            return ForkedProc(reply["pid"])

    def _pick_store_dir(self, store_mem: int) -> str:
        """tmpfs home for the shm segment (plasma convention): big writes
        never generate disk writeback.  Falls back to the session dir
        when the configured dir is missing or can't fit the segment.
        Also sweeps segments leaked by crashed raylets (name embeds the
        creating pid; tmpfs leaks are RAM leaks)."""
        d = CONFIG.object_store_dir
        # sweep leaked segments FIRST: a crashed raylet's multi-GiB
        # segment is the most likely reason the free-space check would
        # fail, and reclaiming it is the point of the sweep
        try:
            for name in os.listdir(d):
                if not name.startswith("ray_tpu_store_"):
                    continue
                try:
                    pid = int(name.split("_")[3])
                    os.kill(pid, 0)
                except (IndexError, ValueError):
                    continue
                except ProcessLookupError:
                    try:
                        os.unlink(os.path.join(d, name))
                    except OSError:
                        pass
                except PermissionError:
                    pass     # pid alive under another user
        except OSError:
            pass
        try:
            st = os.statvfs(d)
            if st.f_bavail * st.f_frsize < store_mem:
                return self.session_dir
        except OSError:
            return self.session_dir
        return d

    def _spawn_cpp_worker(self, worker_id, job_id: Optional[str],
                          env_overrides: Optional[Dict[str, str]]
                          ) -> WorkerHandle:
        """Spawn the native C++ worker runtime (csrc/cpp_worker.cc, the
        reference's cpp/ worker analog) for language=cpp leases.  It
        speaks the same worker protocol, so everything downstream (ready
        wait, lease grant, reaping, kill) is language-blind.  The binary
        is the stock one unless cpp_worker_binary points at a user build
        with more registered functions."""
        binary = CONFIG.cpp_worker_binary
        if not binary:
            # stock build: verify the committed artifact still matches
            # csrc/ sources (rebuilds on mismatch) before spawning it
            from ray_tpu._core import buildcheck
            buildcheck.ensure_fresh(logger=logger)
            binary = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "_core", "cpp_worker")
        if not os.path.exists(binary):
            raise RuntimeError(
                f"cpp worker binary not found at {binary} — build it with "
                "`make -C csrc` or set cpp_worker_binary")
        env = dict(os.environ)
        env.update(env_overrides or {})
        log_prefix = os.path.join(self.session_dir, "logs",
                                  f"cppworker-{worker_id.hex()[:12]}")
        os.makedirs(os.path.dirname(log_prefix), exist_ok=True)
        cmd = [binary,
               "--raylet-host", self.address[0],
               "--raylet-port", str(self.address[1]),
               "--worker-id", worker_id.hex(),
               "--gcs-host", self.gcs_address[0],
               "--gcs-port", str(self.gcs_address[1]),
               "--store-path", self.store_path,
               "--node-id", self.node_id.hex()]
        # flag-override channel the binary understands (cf. Config env
        # resolution): keep the inline threshold consistent across
        # languages when tests/system_config change it — but an explicit
        # per-env user override (env_vars) outranks it, like it would for
        # a Python worker
        env.setdefault("RAY_TPU_INLINE_OBJECT_MAX_BYTES",
                       str(CONFIG.inline_object_max_bytes))
        out_f = open(log_prefix + ".out", "ab")
        err_f = open(log_prefix + ".err", "ab")
        try:
            proc = subprocess.Popen(cmd, env=env, stdout=out_f,
                                    stderr=err_f, cwd=os.getcwd())
        finally:
            out_f.close()
            err_f.close()
        handle = WorkerHandle(worker_id, proc)
        handle.job_id = job_id
        with self._lock:
            self._workers[worker_id.hex()] = handle
        cev.emit(cev.WORKER_SPAWN,
                 f"cpp worker {worker_id.hex()[:8]} spawned",
                 worker_id=worker_id.hex(), job_id=job_id,
                 proc_pid=proc.pid, language="cpp")
        return handle

    def _rpc_register_worker(self, conn, p):
        """Workers call home once their RPC server is up.

        Runs inline on the reader (fast method): the bookkeeping is a
        short lock hold, and the pending-lease scan — which can spawn
        workers and block — is kicked to its own thread so the reader
        never stalls."""
        wid = p["worker_id"]
        with self._lock:
            h = self._workers.get(wid)
            if h is None:
                raise rpc.RpcError(f"unknown worker {wid}")
            h.address = tuple(p["address"])
            h.conn = conn
            conn.peer = ("worker", wid)
            h.ready.set()
        threading.Thread(target=self._dispatch_pending,
                         daemon=True).start()
        return {"ok": True}

    def _wait_worker_ready(self, h: WorkerHandle) -> bool:
        return h.ready.wait(CONFIG.worker_start_timeout_s)

    def _on_worker_dead(self, wid: str, reason: str) -> None:
        with self._lock:
            h = self._workers.pop(wid, None)
            if h is None:
                return
            for q in self._idle.values():
                if wid in q:
                    q.remove(wid)
            lease = h.lease_id
            actor_id = h.actor_id
            oom = wid in self._oom_kills
        logger.info("worker %s dead: %s", wid[:8], reason)
        if h.proc.poll() is None:
            try:
                h.proc.terminate()
            except OSError:
                pass
        clean = reason == "idle trim"
        cev.emit(cev.WORKER_EXIT,
                 f"worker {wid[:8]} exited: {reason}",
                 severity="INFO" if clean else "ERROR",
                 worker_id=wid, actor_id=actor_id, job_id=h.job_id,
                 reason=reason, exit_code=h.proc.returncode, oom=oom)
        if not clean and not self._stopped.is_set():
            # forensics off-path: flight ring + log tail + metrics
            # watermarks -> GCS dossier, referenced by the propagated
            # WorkerCrashedError/ActorDiedError (docs/observability.md)
            threading.Thread(
                target=self._harvest_dossier,
                args=(wid, h, reason, actor_id, oom), daemon=True).start()
        if lease is not None:
            self._release_lease_resources(lease)
        if actor_id is not None:
            try:
                self.gcs.call("actor_failed", {"actor_id": actor_id,
                                               "reason": reason,
                                               "worker_id": wid})
            except (ConnectionError, rpc.RpcError):
                pass
        self._dispatch_pending()

    def _harvest_dossier(self, wid: str, h: WorkerHandle, reason: str,
                         actor_id: Optional[str], oom: bool) -> None:
        """Assemble + store one dead worker's crash dossier.  Best
        effort end to end: forensics must never destabilize the raylet."""
        import json as _json

        from ray_tpu._private.log_monitor import tail_file
        try:
            events = cev.read_flight_file(self.session_dir, wid)
            tail_n = CONFIG.dossier_log_tail_bytes
            # python workers log as worker-<wid12>.*, cpp workers as
            # cppworker-<wid12>.* (_spawn_cpp_worker): try both or the
            # whole cpp class harvests an empty tail
            log_tail = {}
            for s in ("err", "out"):
                for kind in ("worker", "cppworker"):
                    path = os.path.join(self.session_dir, "logs",
                                        f"{kind}-{wid[:12]}.{s}")
                    tail = tail_file(path, tail_n)
                    if tail:
                        break
                log_tail[s] = tail
            # the dead process's last flushed metrics snapshots (its
            # flusher ident is "<mode>-<wid12>"); watermark gauges in
            # there are the per-interval peaks right before death
            metrics = {}
            try:
                suffix = "/worker-" + wid[:12]
                keys = [k for k in self.gcs.kv_keys("metrics/")
                        if k.endswith(suffix)]
                for key in keys[:48]:
                    raw = self.gcs.kv_get(key)
                    if not raw:
                        continue
                    try:
                        blob = _json.loads(raw)
                    except ValueError:
                        continue
                    metrics[key.split("/", 2)[1]] = blob.get("values")
            except (ConnectionError, rpc.RpcError, TimeoutError):
                pass
            dossier = {
                "kind": "worker", "worker_id": wid,
                "node_id": self.node_id.hex(),
                "actor_id": actor_id, "job_id": h.job_id,
                "pid": h.proc.pid, "reason": reason,
                "exit_code": h.proc.returncode, "oom": oom,
                "events": events, "log_tail": log_tail,
                "metrics": metrics,
                "stacks": self._hang_stacks.pop(wid, None),
            }
            self.gcs.call("put_dossier",
                          {"dossier_id": wid, "dossier": dossier},
                          timeout=10)
        except Exception:
            logger.debug("dossier harvest for %s failed", wid[:8],
                         exc_info=True)

    def _kill_worker(self, wid: str, reason: str,
                     force: bool = False,
                     sample_stacks: bool = False) -> None:
        if sample_stacks:
            # hang-timeout kill: flame-sample the still-live process
            # first so the dossier shows WHERE it was stuck (satellite:
            # profiler wired into the event plane).  Bounded, and only
            # on paths that already waited out a multi-second timeout.
            with self._lock:
                h0 = self._workers.get(wid)
                conn = h0.conn if h0 is not None else None
            if conn is not None:
                try:
                    self._hang_stacks[wid] = conn.call(
                        "profile", {"duration": 0.3}, timeout=5)
                except Exception:
                    pass
        with self._lock:
            h = self._workers.get(wid)
            if h is None:
                return
            try:
                # force=SIGKILL for OOM kills: a SIGTERM trap (or a long
                # native call) would let the hog survive untracked while
                # the monitor serially kills innocent workers (reference
                # memory monitor kills with SIGKILL for the same reason).
                # The read of handle.proc AND the signal both stay under
                # _lock: signaling a _PendingProc placeholder must be
                # ordered against _spawn_worker's swap — either the swap
                # already installed the real proc (we signal it), or our
                # terminated mark is still on the placeholder when the
                # spawner checks it under this same lock.  Signals are
                # non-blocking, so holding the lock here is cheap.
                if force:
                    h.proc.kill()
                else:
                    h.proc.terminate()
            except OSError:
                pass
        self._on_worker_dead(wid, reason)

    def _kill_actor_worker(self, actor_id: str) -> None:
        with self._lock:
            victims = [wid for wid, h in self._workers.items()
                       if h.actor_id == actor_id]
        for wid in victims:
            self._kill_worker(wid, "actor killed")

    # ---------------------------------------------------------------- leases
    def _try_acquire(self, need: Dict[str, float],
                     pool_key: Optional[str] = None) -> bool:
        """Deduct ``need`` from the node pool, or from a reserved
        placement-group bundle pool when ``pool_key`` is given."""
        with self._res_lock:
            pool = self.available if pool_key is None \
                else self._bundle_pools.get(pool_key)
            if pool is None:
                return False
            if all(pool.get(r, 0) >= v for r, v in need.items()):
                for r, v in need.items():
                    pool[r] = pool.get(r, 0) - v
                return True
        return False

    def _give_back(self, need: Dict[str, float],
                   pool_key: Optional[str]) -> None:
        with self._res_lock:
            pool = self.available
            if pool_key is not None:
                # if the bundle was dropped meanwhile, resources flow back
                # to the node pool (they were carved out of it originally)
                pool = self._bundle_pools.get(pool_key, self.available)
            for r, v in need.items():
                pool[r] = pool.get(r, 0) + v

    def _release_lease_resources(self, lease_id: str) -> None:
        with self._lock:
            rec = self._leases.pop(lease_id, None)
        if rec:
            self._give_back(rec["need"], rec.get("pool"))
        self._dispatch_pending()

    # ------------------------------------------------- placement-group 2PC
    def _rpc_reserve_bundle(self, conn, p):
        """Phase-1/2 of GCS bundle reservation: carve the bundle's resources
        out of the node pool into a dedicated pool (cf. reference
        PlacementGroupResourceManager, placement_group_resource_manager.h)."""
        key = f"{p['pg_id']}:{int(p['index'])}"
        need = dict(p["resources"])
        with self._res_lock:
            if key in self._bundle_pools:
                return {"ok": True}  # idempotent retry
            if not all(self.available.get(r, 0) >= v
                       for r, v in need.items()):
                return {"ok": False, "reason": "insufficient resources"}
            for r, v in need.items():
                self.available[r] = self.available.get(r, 0) - v
            self._bundle_pools[key] = dict(need)
        return {"ok": True}

    def _rpc_return_bundle(self, conn, p):
        """Release a bundle pool; whatever is currently free in the pool
        returns to the node. In-flight leases drain back via _give_back."""
        key = f"{p['pg_id']}:{int(p['index'])}"
        return {"ok": self._drop_bundle_pool(key)}

    def _drop_bundle_pool(self, key: str) -> bool:
        with self._res_lock:
            pool = self._bundle_pools.pop(key, None)
            if pool:
                for r, v in pool.items():
                    self.available[r] = self.available.get(r, 0) + v
        return pool is not None

    def _release_stale_bundles(self, keys: list) -> None:
        """A heartbeat reply flagged bundle pools the GCS no longer
        places on this node (docs/fault_tolerance.md: pg removed or
        rescheduled after a member node died while this raylet was
        unreachable — the stranded-reservation leak).  Each key is
        re-verified against fresh GCS state before release so a
        flag computed just before a re-reservation landed here can't
        drop a live pool."""
        for key in keys:
            pgid, _, idx = key.partition(":")
            try:
                pg = self.gcs.call("get_placement_group",
                                   {"pg_id": pgid}, timeout=5)
            except (ConnectionError, rpc.RpcError, TimeoutError):
                continue    # can't verify: keep the pool, retry next beat
            if pg is not None:
                placement = pg.get("placement") or []
                try:
                    i = int(idx)
                except ValueError:
                    continue
                ours = (i < len(placement)
                        and placement[i] == self.node_id.hex())
                if pg.get("state") != "CREATED" or ours:
                    continue    # mid-placement or (again) ours: keep
            if self._drop_bundle_pool(key):
                logger.warning("released stranded placement bundle %s",
                               key)
                self._report_event(
                    "WARNING", "BUNDLE_RECLAIMED",
                    f"stranded placement bundle {key} released",
                    bundle=key)

    def _rpc_lease_worker(self, conn, p):
        """Grant a worker lease, spill to another node, or queue.

        cf. CoreWorkerDirectTaskSubmitter::RequestNewWorkerIfNeeded
        (direct_task_transport.cc:325) on the client side; local-first with
        spillback like the reference HybridSchedulingPolicy
        (scheduling/policy/hybrid_scheduling_policy.h:48)."""
        need = dict(p.get("resources", {}))
        need.setdefault("CPU", 1.0)
        bundle = p.get("bundle")  # [pg_id_hex, index] -> lease from the pool
        pool_key = f"{bundle[0]}:{int(bundle[1])}" if bundle else None
        spillback = int(p.get("spillback", 0))
        if self._draining:
            # draining (docs/fault_tolerance.md): no new leases — not
            # even bundle leases; the group is about to lose this node
            # and the event plane is already driving its failover.
            # Redirects only where the client follows them: a bundle
            # lease or a strategy-pinned request (spillback==2) treats
            # the reply as a final grant, so those get the clean error.
            if pool_key is None and spillback < 2:
                target = self._find_remote_candidate(need)
                if target is not None:
                    return {"retry_at": list(target)}
            raise rpc.RpcError(
                "node draining (preemption imminent): "
                f"{self._drain_reason}")
        if pool_key is None and spillback == 0 and \
                CONFIG.locality_aware_scheduling and p.get("arg_locs"):
            # locality-aware placement (docs/object_transfer.md): on the
            # first hop only (no redirect ping-pong), prefer the feasible
            # node already holding the most argument bytes.  Decided
            # before the env build below: a redirected lease must not
            # pay a cold pip install on the node it is about to leave.
            target = self._locality_candidate(need, p["arg_locs"])
            if target is not None:
                _M_LOCALITY_HITS.inc()
                return {"retry_at": list(target)}
        # cold pip-env builds run here, on the requester's own RPC thread
        # (its lease call is what's waiting) — never inside
        # _dispatch_pending, which register/reap paths also drive
        renv = p.get("env")
        if renv and renv.get("pip"):
            from ray_tpu.runtime_env.pip import ensure_pip_env
            try:
                ensure_pip_env(renv["pip"])
            except Exception as e:
                raise rpc.RpcError(f"runtime env setup failed: {e}")
        if pool_key is not None:
            with self._res_lock:
                if pool_key not in self._bundle_pools:
                    raise rpc.RpcError(
                        f"bundle {pool_key} not reserved on this node")
        if pool_key is None and spillback < 2:
            with self._res_lock:
                local_ok = all(self.available.get(r, 0) >= v
                               for r, v in need.items())
            if not local_ok:
                target = self._find_remote_candidate(need)
                if target is not None:
                    return {"retry_at": list(target)}
        if CONFIG.object_prefetch_enabled and p.get("prefetch"):
            # serving this lease here: start pulling its missing large
            # arguments NOW, overlapping worker spawn/lease wait below —
            # one pool job per argument, so they also overlap each other
            for e in p["prefetch"]:
                self._prefetch_pool.submit(self._prefetch_one, e)
        fut_holder: Dict[str, Any] = {}
        event = threading.Event()
        req = {"key": p.get("key", ""), "resources": p.get("resources", {}),
               "job_id": p.get("job_id"), "env": p.get("env") or {},
               "language": p.get("language"),
               "pool": pool_key, "spillback": spillback,
               "t_queued": time.monotonic(),
               "event": event, "out": fut_holder}
        with self._lock:
            self._pending_leases.append(req)
        self._dispatch_pending()
        if not event.wait(CONFIG.worker_lease_timeout_s):
            with self._lock:
                still_queued = req in self._pending_leases
                if still_queued:
                    self._pending_leases.remove(req)
            if still_queued:
                cev.emit(cev.LEASE_TIMEOUT,
                         f"lease for {need} timed out after "
                         f"{CONFIG.worker_lease_timeout_s:.0f}s",
                         severity="WARNING", job_id=p.get("job_id"),
                         resources=dict(need))
                raise rpc.RpcError("lease request timed out (resources busy)")
            # dispatch popped it concurrently with our timeout: a grant is
            # imminent — wait briefly for it instead of leaking the lease
            event.wait(5.0)
            with self._lock:
                if "grant" not in fut_holder and "error" not in fut_holder:
                    # mark abandoned under the lock; if dispatch fills the
                    # grant later it will see the flag and return the lease
                    req["abandoned"] = True
                    raise rpc.RpcError("lease grant lost in dispatch race")
        if "error" in fut_holder:
            raise rpc.RpcError(fut_holder["error"])
        return fut_holder["grant"]

    def _find_remote_candidate(self, need: Dict[str, float]):
        """Another alive node whose reported availability covers `need`."""
        try:
            nodes = self.gcs.call("list_nodes", timeout=5)
        except (ConnectionError, rpc.RemoteError, TimeoutError):
            return None
        for node in nodes:
            if node["node_id"] == self.node_id.hex() or not node["alive"] \
                    or node.get("draining"):
                continue
            if all(node["available"].get(r, 0) >= v for r, v in need.items()):
                return tuple(node["address"])
        return None

    def _dispatch_pending(self) -> None:
        """Satisfy queued lease requests, first-fit: a request blocked on an
        exhausted bundle pool must not head-of-line-block node-pool leases
        (and vice versa) since they draw from independent pools."""
        while True:
            if self._draining:
                # a request that slipped into the queue as the drain
                # flag flipped must still get a redirect, not a grant
                self._sweep_queued_leases()
                return
            with self._lock:
                req = None
                rescan = False
                for cand in self._pending_leases:
                    need = dict(cand["resources"])
                    need.setdefault("CPU", 1.0)
                    pool_key = cand.get("pool")
                    if pool_key is not None and not self._pool_exists(
                            pool_key):
                        # the bundle was removed while we queued: fail fast
                        self._pending_leases.remove(cand)
                        cand["out"]["error"] = \
                            f"placement bundle {pool_key} removed"
                        cand["event"].set()
                        rescan = True
                        break  # deque mutated mid-iteration; rescan
                    if self._try_acquire(need, pool_key):
                        req = cand
                        break
                if req is None:
                    if rescan:
                        continue
                    return
                self._pending_leases.remove(req)
                # reuse an idle worker for this key if possible
                q = self._idle.get(req["key"])
                handle = None
                while q:
                    wid = q.popleft()
                    handle = self._workers.get(wid)
                    if handle is not None:
                        break
            if handle is None:
                try:
                    handle = self._spawn_worker(
                        req["job_id"],
                        self._merged_env(need, req.get("env")),
                        language=req.get("language"))
                except Exception as e:
                    # e.g. pip runtime-env build failure: the lease's
                    # resources must return and the requester must hear
                    # a clean error, not a stall
                    logger.error("worker spawn failed: %s", e)
                    self._give_back(need, pool_key)
                    req["out"]["error"] = f"worker spawn failed: {e}"
                    req["event"].set()
                    continue
                if not self._wait_worker_ready(handle):
                    self._give_back(need, pool_key)
                    req["out"]["error"] = "worker failed to start"
                    req["event"].set()
                    continue
            lease_id = WorkerID.from_random().hex()
            _M_LEASE.observe((time.monotonic() - req["t_queued"]) * 1000.0)
            grant = {
                "lease_id": lease_id,
                "worker_id": handle.worker_id.hex(),
                "address": list(handle.address),
            }
            with self._lock:
                self._leases[lease_id] = {"need": need, "pool": pool_key}
                handle.lease_id = lease_id
                # stamp at lease assignment, not spawn: the OOM policy's
                # LIFO ranks by progress at risk, and a reused idle worker
                # starts fresh work now
                handle.started_at = time.monotonic()
                handle.job_id = req["job_id"]
                abandoned = req.get("abandoned", False)
                if not abandoned:
                    req["out"]["grant"] = grant
            if abandoned:
                # requester gave up during the dispatch race: recycle
                with self._lock:
                    handle.lease_id = None
                    handle.last_idle = time.monotonic()
                    self._idle.setdefault(req["key"], deque()).append(
                        handle.worker_id.hex())
                self._release_lease_resources(lease_id)
            req["event"].set()

    def _pool_exists(self, pool_key: str) -> bool:
        with self._res_lock:
            return pool_key in self._bundle_pools

    def _tpu_env(self, need: Dict[str, float]) -> Dict[str, str]:
        """Workers that lease no TPU must not grab libtpu (hard-part 4)."""
        if need.get("TPU", 0) > 0:
            return {}
        return {"JAX_PLATFORMS": "cpu"}

    def _merged_env(self, need: Dict[str, float],
                    runtime_env: Optional[dict]) -> Dict[str, str]:
        """TPU visibility env + runtime_env env_vars + the serialized
        descriptor the worker applies at startup (working_dir/py_modules)."""
        env = self._tpu_env(need)
        if runtime_env:
            env.update(runtime_env.get("env_vars", {}))
            import json as _json
            env["RAY_TPU_RUNTIME_ENV"] = _json.dumps(runtime_env)
        return env

    def _rpc_return_worker(self, conn, p):
        lease_id = p["lease_id"]
        wid = p["worker_id"]
        key = p.get("key", "")
        with self._lock:
            h = self._workers.get(wid)
            if h is not None and h.lease_id == lease_id:
                h.lease_id = None
                h.last_idle = time.monotonic()
                self._idle.setdefault(key, deque()).append(wid)
        self._release_lease_resources(lease_id)
        return {"ok": True}

    # ---------------------------------------------------------------- actors
    def _rpc_create_actor(self, conn, p):
        """GCS asks us to host an actor: dedicated worker + creation task."""
        t0 = time.monotonic()
        need = dict(p.get("resources", {}))
        need.setdefault("CPU", 1.0)
        bundle = p.get("bundle")
        pool_key = f"{bundle[0]}:{int(bundle[1])}" if bundle else None
        renv = p.get("runtime_env")
        if renv and renv.get("pip"):
            from ray_tpu.runtime_env.pip import ensure_pip_env
            try:
                ensure_pip_env(renv["pip"])   # cold build before resources
            except Exception as e:
                raise rpc.RpcError(f"runtime env setup failed: {e}")
        if not self._try_acquire(need, pool_key):
            raise rpc.RpcError("resources unavailable for actor")
        try:
            handle = self._spawn_worker(
                None, self._merged_env(need, p.get("runtime_env")),
                language=p.get("language"))
        except Exception as e:
            self._give_back(need, pool_key)
            raise rpc.RpcError(f"actor worker spawn failed: {e}")
        t_spawn = time.monotonic()
        if not self._wait_worker_ready(handle):
            self._give_back(need, pool_key)
            raise rpc.RpcError("actor worker failed to start")
        t_ready = time.monotonic()
        lease_id = "actor-" + p["actor_id"]
        with self._lock:
            self._leases[lease_id] = {"need": need, "pool": pool_key}
            handle.lease_id = lease_id
            handle.started_at = time.monotonic()
            handle.actor_id = p["actor_id"]
        try:
            handle.conn.call("create_actor", {
                "actor_id": p["actor_id"], "spec": p["spec"]},
                timeout=CONFIG.actor_creation_timeout_s)
        except (rpc.RemoteError, ConnectionError, TimeoutError) as e:
            # a TimeoutError here is a hang-timeout kill: sample the
            # wedged __init__'s stacks into the dossier before killing
            self._kill_worker(handle.worker_id.hex(),
                              f"actor init failed: {e}",
                              sample_stacks=isinstance(e, TimeoutError))
            raise rpc.RpcError(f"actor init failed: {e}")
        logger.info(
            "actor %s hosted: spawn %.0fms ready %.0fms init %.0fms",
            p["actor_id"][:8], (t_spawn - t0) * 1e3,
            (t_ready - t_spawn) * 1e3,
            (time.monotonic() - t_ready) * 1e3)
        return {"ok": True, "address": list(handle.address)}

    # ---------------------------------------------------------------- objects
    def _rpc_fetch_object(self, conn, p):
        """Whole-object fetch: one chunk spanning the object."""
        return self._rpc_fetch_object_chunk(conn, p)

    def _rpc_fetch_object_chunk(self, conn, p):
        """Chunked inter-node transfer: one [offset, offset+length) slice
        per call, so a multi-GB object never occupies a multi-GB RPC frame
        on either side (cf. ObjectManager::Push chunked transfer,
        object_manager.cc:338 / push_manager.h:29).

        Runs inline on the reader thread (fast-method registry): a shm hit
        costs one pin plus an enqueued reply frame.  With ``oob`` the
        reply carries the shm slice itself as a pickle-5 out-of-band
        buffer on a *stable* frame — no ``bytes()`` copy per chunk; the
        pin is held until the write drains to the socket (rpc.py stable
        frames).  The spilled/absent path parks behind a Deferred on the
        dispatch pool so the reader never blocks on disk or restores."""
        from ray_tpu._private.ids import ObjectID
        oid = ObjectID(p["object_id"])
        res = self.store.get(oid, timeout=0.0)
        if res is not None:
            value, on_sent = self._chunk_reply(oid, res, p)
            if on_sent is None:
                return value
            d = rpc.Deferred()
            d.resolve(value, stable=True, on_sent=on_sent)
            return d
        d = rpc.Deferred()

        def run():
            try:
                value, on_sent = self._fetch_spilled_chunk(oid, p)
                d.resolve(value, stable=on_sent is not None,
                          on_sent=on_sent)
            except BaseException as e:  # noqa: BLE001 - crosses the wire
                d.fail(e)

        rpc._dispatch_pool().submit(run)
        return d

    def _chunk_reply(self, oid, res, p):
        """-> (reply value, on_sent or None) for a pinned shm hit."""
        buf, meta = res
        total = len(buf)
        off = int(p.get("offset", 0))
        end = min(off + int(p.get("length", total)), total)
        _M_CHUNKS_SERVED.inc()
        _M_CHUNK_BYTES_OUT.inc(max(0, end - off))
        if not p.get("oob"):
            # legacy/serial callers: copy out and release immediately
            try:
                return ({"total": total, "meta": meta,
                         "data": bytes(buf[off:end])}, None)
            finally:
                buf.release()
                self.store.release(oid)
        piece = buf[off:end]

        def _release(piece=piece, buf=buf, oid=oid):
            # fires exactly once when the frame drains (or is dropped):
            # the only store pin this chunk ever took ends here
            piece.release()
            buf.release()
            self.store.release(oid)

        return ({"total": total, "meta": meta,
                 "data": pickle.PickleBuffer(piece)}, _release)

    def _rpc_object_pins(self, conn, p):
        """Pin counts of sealed local objects (tests + `ray-tpu memory`
        debugging: is a prefetch pin / reader still holding this?)."""
        want = set(p.get("object_ids", ())) if p.get("object_ids") else None
        out = {}
        for oid, _size, _tick, pins in self.store.list_objects():
            if want is None or oid.binary() in want:
                out[oid.hex()] = pins
        return out

    # ------------------------------------------------- argument prefetch
    def _gcs_nodes(self, max_age: float) -> list:
        """list_nodes snapshot at most ``max_age`` seconds old ([] when
        the GCS is unreachable and nothing is cached).  One cache serves
        locality placement and prefetch address resolution — the lease
        path must not pay a GCS round trip per request."""
        ts, nodes = self._nodes_snapshot
        now = time.monotonic()
        if now - ts > max_age:
            try:
                nodes = self.gcs.call("list_nodes", timeout=2)
            except (ConnectionError, rpc.RpcError, TimeoutError):
                return nodes  # stale beats nothing
            self._nodes_snapshot = (now, nodes)
        return nodes

    def _peer_address(self, node_hex: str) -> Optional[Tuple[str, int]]:
        """node hex -> raylet address (prefetch pulls resolve many
        sources per lease wave, so tolerate a 5 s-stale snapshot)."""
        for n in self._gcs_nodes(5.0):
            if n["node_id"] == node_hex and n.get("alive"):
                return tuple(n["address"])
        return None

    def _prefetch_one(self, e: dict) -> None:
        """Pull one lease argument into local shm concurrently with
        worker lease/startup (docs/object_transfer.md: transfer overlaps
        scheduling instead of serializing after it).  Runs on the
        bounded prefetch pool; the lease grant never waits for it."""
        from ray_tpu._private.ids import ObjectID
        ob = bytes(e["object_id"])
        oid = ObjectID(ob)
        _M_PREFETCH_REQS.inc()
        with self._prefetch_lock:
            if ob in self._prefetch_pins or ob in self._prefetch_inflight:
                _M_PREFETCH_HITS.inc()
                return
            self._prefetch_inflight.add(ob)
        try:
            with self._lock:
                spilled_here = ob in self._spilled
            if spilled_here or self.store.contains(oid):
                # already on this node (shm or our spill dir): the
                # task's own fetch restores/pins it on demand
                _M_PREFETCH_HITS.inc()
                return
            sources = [nh for nh in e.get("locations", ())
                       if nh != self.node_id.hex()]
            if not sources:
                return
            out = self._puller.pull(
                oid, sources,
                deadline=time.monotonic() + CONFIG.prefetch_pin_ttl_s,
                publish_small=True)
            if out.status != "ok" or not out.published:
                return
            with self._prefetch_lock:
                freed = ob in self._prefetch_freed
                if not freed:
                    self._prefetch_pins[ob] = (
                        out.data,
                        time.monotonic() + CONFIG.prefetch_pin_ttl_s)
            if freed:
                # freed while we were pulling: discard the resurrected
                # copy instead of pinning bytes nobody can ever use
                out.data.release()
                self.store.release(oid)
                self.store.delete(oid)
                return
            _M_PREFETCH_BYTES.inc(out.bytes)
            owner = e.get("owner")
            if owner:
                # grow the owner's location set: the final free must
                # sweep this copy, and later pulls can stripe off us
                try:
                    conn = self._conn_cache.get(tuple(owner))
                    conn.call_async(
                        "report_object_location",
                        {"object_id": ob,
                         "node_id": self.node_id.hex(),
                         "size": out.bytes})
                except Exception:
                    pass
        except Exception:
            logger.exception("argument prefetch failed for %s",
                             oid.hex()[:12])
        finally:
            with self._prefetch_lock:
                self._prefetch_inflight.discard(ob)
                self._prefetch_freed.discard(ob)

    def _release_prefetch_pin(self, ob: bytes) -> None:
        with self._prefetch_lock:
            rec = self._prefetch_pins.pop(ob, None)
            self._evac_keep.discard(ob)
        if rec is None:
            return
        view, _exp = rec
        try:
            view.release()
        except (BufferError, AttributeError):
            pass
        from ray_tpu._private.ids import ObjectID
        self.store.release(ObjectID(ob))

    def _reap_prefetch_pins(self) -> None:
        """Safety net (spill loop, every 0.2 s): a pin whose lease never
        dispatched — request timed out, task cancelled before dispatch —
        must not keep its bytes unevictable forever."""
        now = time.monotonic()
        with self._prefetch_lock:
            expired = [ob for ob, (_v, exp) in self._prefetch_pins.items()
                       if exp <= now]
        for ob in expired:
            self._release_prefetch_pin(ob)

    def _locality_candidate(self, need: Dict[str, float],
                            arg_locs: Dict[str, float]):
        """The feasible node already holding strictly more argument bytes
        than this one, if any (reference locality-aware lease policy /
        locality_data_provider): its address, else None."""
        local_bytes = float(arg_locs.get(self.node_id.hex(), 0.0))
        best = None
        best_bytes = local_bytes
        nodes = self._gcs_nodes(1.0)
        for node in nodes:
            nh = node["node_id"]
            if nh == self.node_id.hex() or not node.get("alive") \
                    or node.get("draining"):
                continue
            nbytes = float(arg_locs.get(nh, 0.0))
            if nbytes <= best_bytes or \
                    nbytes < CONFIG.locality_min_arg_bytes:
                continue
            if all(node["available"].get(r, 0) >= v
                   for r, v in need.items()):
                best = tuple(node["address"])
                best_bytes = nbytes
        return best

    def _rpc_list_workers(self, conn, p):
        """Registered worker processes on this node (state API fan-out)."""
        with self._lock:
            return [{
                "worker_id": wid,
                "address": list(h.address) if h.address else None,
                "actor_id": h.actor_id,
                "job_id": h.job_id,
                "pid": h.proc.pid,
                "alive": h.proc.poll() is None,
            } for wid, h in self._workers.items()]

    def _rpc_store_stats(self, conn, p):
        return self.store.stats()

    def _rpc_node_info(self, conn, p):
        with self._res_lock:
            return {"node_id": self.node_id.hex(),
                    "resources": dict(self.resources),
                    "available": dict(self.available),
                    "bundles": list(self._bundle_pools),
                    "draining": self._draining,
                    "num_workers": len(self._workers),
                    "oom_kill_count": self._oom_kill_count,
                    "memory_usage": self._memory_monitor.last_usage,
                    "store_path": self.store_path}

    # ------------------------------------------------------------------ stop
    def shutdown(self) -> None:
        self._stopped.set()
        # unhook telemetry publishing bound to this raylet's GCS client
        rtm.detach(self.gcs.kv_put)
        rtm.remove_gauge_callback("ray_tpu_worker_pool_size")
        cev.detach(self._events_recorder)
        if self._log_monitor is not None:
            self._log_monitor.stop()
        with self._lock:
            handles = list(self._workers.values())
            self._workers.clear()
        for h in handles:
            try:
                h.proc.terminate()
            except OSError:
                pass
        for h in handles:
            try:
                h.proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                h.proc.kill()
        if self._zygote_conn is not None:
            try:
                self._zygote_conn.close()
            except OSError:
                pass
        if self._zygote_proc is not None:
            self._zygote_proc.terminate()
            try:
                self._zygote_proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                self._zygote_proc.kill()
        self._server.stop()
        self._prefetch_pool.shutdown(wait=False)
        self._conn_cache.close()
        with self._prefetch_lock:
            pins = list(self._prefetch_pins)
        for ob in pins:
            self._release_prefetch_pin(ob)
        try:
            self.gcs.close()
        except Exception:
            pass
        self.store.close()
        self.store.unlink()
        import shutil
        shutil.rmtree(self._spill_dir, ignore_errors=True)


def main():  # pragma: no cover - subprocess entry
    import argparse
    import json
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--address-file", default=None)
    parser.add_argument("--labels", default="{}")
    args = parser.parse_args()
    from ray_tpu._private.logging_utils import (enable_stack_dumps,
                                                 setup_component_logging)
    setup_component_logging("raylet", args.session_dir)
    enable_stack_dumps(args.session_dir)
    resources = json.loads(args.resources) or None
    raylet = Raylet((args.gcs_host, args.gcs_port), args.session_dir,
                    resources=resources,
                    object_store_memory=args.object_store_memory or None,
                    labels=json.loads(args.labels) or None)
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": raylet.address[0], "port": raylet.address[1],
                       "node_id": raylet.node_id.hex(),
                       "store_path": raylet.store_path}, f)
        os.replace(tmp, args.address_file)
    logger.info("raylet %s serving at %s", raylet.node_id.hex()[:8],
                raylet.address)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        raylet.shutdown()


if __name__ == "__main__":
    main()
