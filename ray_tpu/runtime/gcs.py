"""Global Control Service: the head-node daemon.

TPU-native analog of the reference GCS
(/root/reference/src/ray/gcs/gcs_server/gcs_server.cc:121-181 wires the same
module set): node table + health checking (GcsNodeManager/GcsHealthCheckManager),
actor directory + restart FSM (GcsActorManager, gcs_actor_manager.cc:240/1233),
job table (GcsJobManager), internal KV (GcsKVManager — function/config store),
pubsub channels (long-poll in the reference, push-based here since our RPC
connections are duplex), and placement groups.

Storage is pluggable like the reference's RedisStoreClient/InMemoryStoreClient
(store_client/*.h): in-memory dict by default, optional file-snapshot backend
so a restarted GCS replays state (GcsInitData replay analog).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import rpc
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu._private.logging_utils import get_logger

logger = get_logger("gcs")

# Actor FSM states (cf. reference rpc::ActorTableData::ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class GcsServer:
    """All control state for one cluster; serves the RPC surface.

    With ``persist_path`` set, durability is two-tier (reference: every
    table mutation writes through to the store client,
    store_client/redis_store_client.h:28; GcsInitData replays it at
    gcs_server.cc:121-181):

    * a **write-ahead journal** (``<persist_path>.wal``) gets one
      length-prefixed record per mutation, synchronously, before the
      mutating RPC returns — so a SIGKILL directly after an
      acknowledged mutation loses nothing (fsync is opt-in via
      ``gcs_wal_fsync``; without it, records survive process death but
      not host power loss);
    * a **snapshot thread** compacts the full tables into an atomic
      pickle (tmp+rename) every ``gcs_snapshot_interval_s`` while dirty,
      rotating the journal so replay length stays bounded.

    Recovery loads the snapshot (if any), then replays journal records
    with a sequence number newer than the snapshot's.  Records carry
    absolute values (table, key, value-or-tombstone), so re-applying an
    already-compacted record is idempotent.  Task events and the
    component-event ring are deliberately ephemeral."""

    _TOMBSTONE = "__gcs_wal_tombstone__"

    # Handlers that only take self._lock, never block, never WAL and never
    # call back over the connection: the RPC layer runs them inline on the
    # reader thread (rpc.py fast-method registry), skipping the dispatch-
    # pool hop on the control plane's highest-frequency calls (liveness
    # heartbeats, KV reads, actor-resolution polls).
    FAST_METHODS = frozenset({
        "heartbeat", "kv_get", "kv_exists", "kv_keys", "list_nodes",
        "get_actor", "get_placement_group",
    })

    SNAPSHOT_TABLES = ("_nodes", "_actors", "_named_actors", "_jobs",
                      "_kv", "_placement_groups")

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None):
        self._lock = threading.RLock()
        self._persist_path = persist_path
        self._dirty = threading.Event()
        # node_id hex -> {address, resources, available, last_heartbeat, alive}
        self._nodes: Dict[str, Dict[str, Any]] = {}
        # actor_id hex -> actor table entry
        self._actors: Dict[str, Dict[str, Any]] = {}
        self._named_actors: Dict[Tuple[str, str], str] = {}  # (ns, name) -> id
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._kv: Dict[str, bytes] = {}
        from ray_tpu._private.task_events import GcsTaskTable
        self._task_table = GcsTaskTable()
        # cluster event plane (docs/observability.md): sharded,
        # retention-bounded table of typed lifecycle events aggregated
        # from every process, plus the bounded crash-dossier store the
        # raylets fill on abnormal worker exits.  Ephemeral (never
        # WALed), like task events and metrics.
        from ray_tpu._private import cluster_events as cev
        self._events_table = cev.GcsClusterEventTable()
        # training performance plane (docs/observability.md): per-run
        # step table aggregating every rank's phase clocks, straggler
        # detection edge-triggering TRAIN_STRAGGLER into the event
        # table, and the goodput-ledger store.  Ephemeral like task
        # events and metrics.
        from ray_tpu._private import step_stats as sst
        self._step_stats = sst.GcsStepStatsTable(emit=self.record_event)
        # distributed request tracing plane (docs/observability.md):
        # trace-indexed span store fed by every process's span-buffer
        # flusher; root spans carrying a dossier_id cross-link the
        # dossier back to the trace.  Ephemeral like events/metrics.
        from ray_tpu.util.tracing import tracing_helper as trh
        self._span_table = trh.GcsSpanTable(
            on_dossier_link=self._link_dossier_trace)
        # metrics-history plane (docs/observability.md): every metrics
        # KV write is additionally folded into a bounded multi-
        # resolution ring per series, and the recovery auditor derives
        # drain/failover/heal episodes from the event stream.
        # Ephemeral like all other observability tables.
        from ray_tpu._private import metrics_history as mh
        self._history = mh.GcsMetricsHistoryTable()
        self._auditor = mh.RecoveryAuditor()
        self._dossiers: Dict[str, dict] = {}
        self._dossier_order: deque = deque()
        # evacuated-object location hints (docs/fault_tolerance.md):
        # oid hex -> (node hex set, ts).  Written by draining raylets as
        # they ship primary copies to survivors; read by owners whose
        # location set emptied, BEFORE lineage reconstruction.  Bounded
        # (dict insertion order IS the eviction order — refreshes
        # reinsert, so the cap always drops the stalest hint) +
        # TTL-swept; ephemeral (an expired hint degrades to
        # reconstruction, never to a wrong answer).
        self._evac: Dict[str, Tuple[set, float]] = {}
        self._placement_groups: Dict[str, Dict[str, Any]] = {}
        # channel -> list of (conn, subscriber key)
        self._subs: Dict[str, List[rpc.Connection]] = {}
        self._node_conns: Dict[str, rpc.Connection] = {}
        self._server = rpc.Server(self._handle, host=host, port=port,
                                  on_disconnect=self._on_disconnect,
                                  fast_methods=self.FAST_METHODS)
        self._stopped = threading.Event()
        self._retry_inflight = threading.Event()
        from ray_tpu._core.scheduler import make_scheduler
        self._cluster_scheduler = make_scheduler(
            spill_threshold=CONFIG.scheduler_spill_threshold)
        self._wal_lock = threading.Lock()
        self._wal_seq = 0
        self._wal_fh = None
        if persist_path:
            self._recover(persist_path)
            if CONFIG.gcs_wal_enabled:
                self._wal_fh = open(persist_path + ".wal", "ab")
        # runtime telemetry: the GCS flushes its own hot-path metrics
        # (RPC dispatch latency etc.) straight into its KV table — no
        # WAL record, metrics are ephemeral monitoring data
        from ray_tpu._private import runtime_metrics as rtm
        rtm.attach(self._metrics_kv_put, ident="gcs")
        self._health_thread = threading.Thread(target=self._health_loop,
                                               daemon=True)
        self._health_thread.start()
        if persist_path:
            self._snap_thread = threading.Thread(target=self._snapshot_loop,
                                                 daemon=True)
            self._snap_thread.start()

    # ------------------------------------------------------------ persistence
    def _mark_dirty(self, *hints) -> None:
        """Mark the snapshot dirty and journal the named entries.

        ``hints`` are ``(table_attr, key)`` pairs identifying what the
        caller just mutated; each becomes one synchronous WAL record of
        the entry's **current** value (``key=None`` journals the whole
        table — used where one RPC fans out over many entries, e.g. a
        job finish killing its actors).  Callers that can't name what
        changed pass nothing and fall back to snapshot-tick durability."""
        if not self._persist_path:
            return
        self._dirty.set()
        if self._wal_fh is None or not hints:
            return
        import pickle
        import struct
        try:
            # self._lock before _wal_lock everywhere: the value read and
            # its sequence number must agree, or replay could finish on a
            # stale value for a key mutated concurrently.  The disk write
            # happens OUTSIDE self._lock so fsync latency never stalls
            # unrelated RPCs; replay sorts records by seq, so two threads
            # landing frames out of file order is harmless.
            with self._lock:
                with self._wal_lock:
                    frames = []
                    for table, key in hints:
                        tbl = getattr(self, table)
                        if key is None:
                            value = dict(tbl)
                        else:
                            value = tbl.get(key, self._TOMBSTONE)
                        self._wal_seq += 1
                        rec = pickle.dumps(
                            (self._wal_seq, table, key, value))
                        frames.append(struct.pack(">I", len(rec)) + rec)
            with self._wal_lock:
                if self._wal_fh is None:
                    return
                self._wal_fh.write(b"".join(frames))
                self._wal_fh.flush()
                if CONFIG.gcs_wal_fsync:
                    os.fsync(self._wal_fh.fileno())
        except Exception:
            logger.exception("GCS WAL append failed (snapshot tick still "
                             "covers this mutation)")

    def _snapshot_loop(self) -> None:
        while not self._stopped.wait(CONFIG.gcs_snapshot_interval_s):
            if not self._dirty.is_set():
                continue
            self._dirty.clear()
            try:
                self._write_snapshot()
            except Exception:
                logger.exception("GCS snapshot write failed")
        # final snapshot on clean stop so nothing since the last tick is lost
        if self._dirty.is_set():
            try:
                self._write_snapshot()
            except Exception:
                pass

    def _wal_old_files(self) -> list:
        """Rotated journal segments on disk, oldest first (the rotation
        seq is embedded in the name)."""
        import glob
        out = []
        for p in glob.glob(self._persist_path + ".wal.old.*"):
            try:
                out.append((int(p.rsplit(".", 1)[1]), p))
            except ValueError:
                continue
        legacy = self._persist_path + ".wal.old"  # pre-unique-name builds
        if os.path.exists(legacy):
            out.append((-1, legacy))
        return [p for _, p in sorted(out)]

    def _write_snapshot(self) -> None:
        import pickle
        with self._lock:
            with self._wal_lock:
                blob = pickle.dumps(
                    {"__v": 2, "wal_seq": self._wal_seq,
                     "tables": {t: getattr(self, t)
                                for t in self.SNAPSHOT_TABLES}})
                # rotate the journal inside the locks: records after the
                # pickle point land in the fresh file and survive the
                # compaction; records before it are covered by the pickle.
                # Rotation uses a UNIQUE name per compaction — if the
                # snapshot write below fails (disk full), earlier rotated
                # segments must survive untouched or their acked records
                # would have no on-disk copy; replay seq-filters overlaps
                if self._wal_fh is not None:
                    self._wal_fh.close()
                    os.replace(self._persist_path + ".wal",
                               f"{self._persist_path}.wal.old."
                               f"{self._wal_seq}")
                    self._wal_fh = open(self._persist_path + ".wal", "ab")
        tmp = f"{self._persist_path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self._persist_path)
        # only now are all rotated segments (records <= pickled wal_seq)
        # fully covered by a durable snapshot
        for p in self._wal_old_files():
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    @classmethod
    def _read_wal_records(cls, path: str) -> list:
        """Records from one journal file, tolerating a torn final write."""
        import pickle
        import struct
        out = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return out
        off = 0
        while off + 4 <= len(data):
            (n,) = struct.unpack_from(">I", data, off)
            if off + 4 + n > len(data):
                break  # torn tail: the append died mid-record
            try:
                out.append(pickle.loads(data[off + 4:off + 4 + n]))
            except Exception:
                break
            off += 4 + n
        return out

    def _recover(self, path: str) -> None:
        """Snapshot + journal replay (GcsInitData analog).  Runs during
        construction, before the address file is published — no client
        can reach the server yet, so replay is effectively single-
        threaded."""
        import pickle
        base_seq = 0
        loaded = False
        if os.path.exists(path):
            with open(path, "rb") as f:
                state = pickle.load(f)
            if "__v" in state:
                tables, base_seq = state["tables"], state["wal_seq"]
            else:  # v1 flat-dict snapshot from before the WAL existed
                tables = state
            with self._lock:
                for t in self.SNAPSHOT_TABLES:
                    getattr(self, t).update(tables.get(t, {}))
            loaded = True
        # journals are ALWAYS replayed, even with gcs_wal_enabled=False —
        # the flag governs writing; records a previous (WAL-on) incarnation
        # acked must never be dropped just because the operator toggled it.
        # Records apply in seq order (concurrent appenders may land frames
        # out of file order), filtered against the snapshot's seq.
        records = []
        for wal in self._wal_old_files() + [path + ".wal"]:
            records.extend(self._read_wal_records(wal))
        records.sort(key=lambda r: r[0])
        replayed = 0
        for seq, table, key, value in records:
            self._wal_seq = max(self._wal_seq, seq)
            if seq <= base_seq or table not in self.SNAPSHOT_TABLES:
                continue
            tbl = getattr(self, table)
            if key is None:
                tbl.clear()
                tbl.update(value)
            elif value == self._TOMBSTONE:
                tbl.pop(key, None)
            else:
                tbl[key] = value
            replayed += 1
        self._wal_seq = max(self._wal_seq, base_seq)
        if not CONFIG.gcs_wal_enabled and replayed:
            # WAL now off: nothing will rotate these files again, and a
            # future WAL-on incarnation would replay them over a NEWER
            # snapshot, resurrecting later-deleted state.  Fold them into
            # a snapshot right now, then drop them.
            self._write_snapshot()
            try:
                os.remove(path + ".wal")
            except FileNotFoundError:
                pass
        if loaded or replayed:
            self._post_recover(path, replayed)

    def _post_recover(self, path: str, replayed: int) -> None:
        now = time.monotonic()
        with self._lock:
            for node in self._nodes.values():
                # give restored nodes a fresh grace period to heartbeat in;
                # monotonic timestamps from the old process are meaningless
                node["last_heartbeat"] = now
                node["last_busy"] = now
                if node["alive"]:
                    self._cluster_scheduler.update_node(
                        node["node_id"], node["resources"],
                        node["available"], True)
            for a in self._actors.values():
                # in-flight dispatches died with the old process: let the
                # retry machinery re-drive anything not ALIVE/DEAD
                if a.get("state") in (PENDING_CREATION, RESTARTING):
                    a["dispatched"] = False
                    a.pop("retry_delay", None)
        logger.info("GCS state restored from %s (+%d WAL records): "
                    "%d nodes, %d actors, %d jobs, %d kv keys, %d pgs",
                    path, replayed, len(self._nodes), len(self._actors),
                    len(self._jobs), len(self._kv),
                    len(self._placement_groups))
        threading.Thread(target=self._retry_after_reattach,
                         daemon=True).start()

    def _retry_after_reattach(self) -> None:
        """Post-restore retry kick: wait for restored alive nodes to
        re-attach their push connections (first heartbeat) before driving
        pending actors — dispatching into an empty _node_conns would burn
        every restart attempt in milliseconds on 'no connection to node'."""
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._lock:
                alive = [n["node_id"] for n in self._nodes.values()
                         if n["alive"]]
                if alive and all(nid in self._node_conns for nid in alive):
                    break
            time.sleep(0.05)
        self._retry_pending_actors()

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def stop(self) -> None:
        self._stopped.set()
        from ray_tpu._private import runtime_metrics as rtm
        rtm.detach(self._metrics_kv_put)
        self._server.stop()
        snap = getattr(self, "_snap_thread", None)
        if snap is not None:
            snap.join(timeout=5)  # let the final compaction finish
        with self._wal_lock:
            if self._wal_fh is not None:
                self._wal_fh.close()
                self._wal_fh = None

    # RPCs that change persisted tables → the WAL hints for what they
    # touch; _handle journals + marks dirty after any of them.  Handlers
    # whose fan-out the payload can't name (finish_job kills the job's
    # actors, actor_failed drives the restart FSM) journal from inside
    # the transition instead and are mapped to no hints here.
    # metrics/ keys are ephemeral monitoring data republished every
    # flush interval by every process: journaling them would grow the
    # WAL without bound and pay a per-metric fsync, and even marking
    # the snapshot dirty would make an otherwise-idle cluster rewrite
    # its snapshot continuously — so their mutations skip durability
    _SKIP_DURABILITY = object()

    _MUTATING_RPCS: Dict[str, Any] = {
        "register_node": lambda p: (("_nodes", p["node_id"]),),
        "register_job": lambda p: (("_jobs", p["job_id"]),),
        "finish_job": lambda p: (),
        "kv_put": lambda p: (
            GcsServer._SKIP_DURABILITY
            if p["key"].startswith("metrics/")
            else (("_kv", p["key"]),)),
        "kv_del": lambda p: (
            GcsServer._SKIP_DURABILITY
            if p["key"].startswith("metrics/")
            else (("_kv", p["key"]),)),
        "register_actor": lambda p: (("_actors", p["actor_id"]),
                                     ("_named_actors", None)),
        "actor_ready": lambda p: (("_actors", p["actor_id"]),),
        "actor_failed": lambda p: (),
        "kill_actor": lambda p: (("_actors", p["actor_id"]),
                                 ("_named_actors", None)),
        "create_placement_group": lambda p: (
            ("_placement_groups", p["pg_id"]),),
        # actor deaths from PG removal journal individually via
        # _on_actor_failure's own ("_actors", aid) hint
        "remove_placement_group": lambda p: (
            ("_placement_groups", p["pg_id"]), ("_named_actors", None)),
    }

    def _rpc_profile(self, conn, p):
        """Flame-sample the GCS process itself (reporter_agent analog)."""
        from ray_tpu._private.profiler import sample_folded
        return sample_folded(float((p or {}).get("duration", 2.0)))

    # ------------------------------------------------------ component events
    def _rpc_report_event(self, conn, p):
        """Legacy single-event report (reference event.cc schema:
        severity/label/message/source + custom fields); folded into the
        typed cluster event table — ``label`` becomes the event type."""
        ev = {"ts": p.get("ts") or time.time(),
              "severity": p.get("severity", "INFO"),
              "source": p.get("source", "unknown"),
              "type": p.get("label", "") or "EVENT",
              "message": p.get("message", "")}
        for k, v in (p.get("fields") or {}).items():
            if v is not None:
                ev.setdefault(k, v)
        self._events_table.put([ev])
        self._audit_events([ev])
        self._publish("events", ev)
        return {"ok": True}

    def record_event(self, severity: str, source: str, label: str,
                     message: str, **fields) -> None:
        """In-process emission for the GCS's own transitions.  Honors
        the event-plane kill switch (RAY_TPU_EVENTS=0): ambient
        instrumentation goes quiet; explicit client ``report_event``
        calls still land (a user API action, not instrumentation)."""
        from ray_tpu._private import cluster_events as cev
        # raylint: disable=kill-switch -- one explicit control-plane RPC per call; an env read is noise next to the RPC itself, and the kill-switch test flips the env at runtime
        if not cev.enabled():
            return
        self._rpc_report_event(None, {
            "severity": severity, "source": source, "label": label,
            "message": message, "fields": fields})

    def _rpc_report_cluster_events(self, conn, p):
        """Batched typed-event flush from a process's EventRecorder
        (cluster_events.py flusher cadence)."""
        events = p.get("events") or []
        dropped = self._events_table.put(events)
        self._audit_events(events)
        for ev in events:
            self._publish("events", ev)
        return {"dropped": dropped}

    def _audit_events(self, events) -> None:
        """Feed freshly landed events to the recovery auditor (sixth
        plane, metrics_history.py): it derives drain/failover/heal
        episodes and never emits events itself (no recursion)."""
        from ray_tpu._private import metrics_history as mh
        if mh.history_on():
            self._auditor.observe(events)

    def _rpc_list_cluster_events(self, conn, p):
        return self._events_table.list(
            node_id=p.get("node_id"), job_id=p.get("job_id"),
            actor_id=p.get("actor_id"), worker_id=p.get("worker_id"),
            severity=p.get("severity"),
            min_severity=p.get("min_severity"),
            etype=p.get("type"), source=p.get("source"),
            limit=int(p.get("limit", 1000)))

    def _rpc_cluster_event_stats(self, conn, p):
        out = self._events_table.stats()
        out["counts_by_type"] = self._events_table.counts_by_type()
        return out

    def _rpc_list_events(self, conn, p):
        """Legacy shape (dashboard Events page, PARITY tests): typed
        records rendered back as label/message/fields rows."""
        limit = int(p.get("limit", 200)) if p else 200
        sev = (p or {}).get("severity")
        std = ("ts", "type", "severity", "source", "message")
        out = []
        for ev in self._events_table.list(severity=sev, limit=limit):
            out.append({"ts": ev.get("ts"),
                        "severity": ev.get("severity", "INFO"),
                        "source": ev.get("source", ""),
                        "label": ev.get("type", ""),
                        "message": ev.get("message", ""),
                        "fields": {k: v for k, v in ev.items()
                                   if k not in std}})
        return out[-limit:]

    # ------------------------------------------------- training perf plane
    def _rpc_report_step_stats(self, conn, p):
        """Batched per-step reports (and end-of-run goodput ledgers)
        from each rank's step-stats flusher (_private/step_stats.py)."""
        return {"dropped": self._step_stats.put(p.get("reports") or [])}

    def _rpc_list_step_stats(self, conn, p):
        """Run directory + recent per-step cross-rank records.  With
        ``run`` (id or group prefix) includes that run's step rows;
        the run rows carry rank metadata (worker id/address) so
        ``ray-tpu profile --group`` can gang-fan-out."""
        run = p.get("run")
        out = {"runs": self._step_stats.list_runs(
            run=run, limit=int(p.get("limit", 100)))}
        if run:
            out["steps"] = self._step_stats.steps(
                run, limit=int(p.get("steps_limit", 64)))
        out["stats"] = self._step_stats.stats()
        return out

    def _rpc_training_summary(self, conn, p):
        """The goodput-ledger view of one run (latest by default)."""
        return self._step_stats.summary(p.get("run"))

    # ------------------------------------------------------- tracing plane
    def _rpc_report_spans(self, conn, p):
        """Batched span flush from a process's SpanBuffer
        (tracing_helper.py flusher cadence)."""
        return {"dropped": self._span_table.put(p.get("spans") or [])}

    def _rpc_list_traces(self, conn, p):
        return self._span_table.list(
            slo_violations=bool(p.get("slo_violations")),
            route=p.get("route"), status=p.get("status"),
            since=p.get("since"), limit=int(p.get("limit", 100)))

    def _rpc_get_trace(self, conn, p):
        return self._span_table.get(p.get("trace_id") or "")

    def _rpc_trace_stats(self, conn, p):
        return self._span_table.stats()

    # ---------------------------------------------- metrics-history plane
    def _rpc_list_metrics_history(self, conn, p):
        """Windowed points for a series (or all series of a metric):
        parsed payloads oldest-first from the retention rings."""
        p = p or {}
        return self._history.query(
            name=p.get("name"), ident=p.get("ident"),
            since=p.get("since"), resolution=p.get("resolution"),
            limit=int(p.get("limit", 2000)))

    def _rpc_metrics_history_stats(self, conn, p):
        out = self._history.stats()
        if (p or {}).get("series"):
            out["series_index"] = self._history.series()
        return out

    def _rpc_list_recovery_episodes(self, conn, p):
        p = p or {}
        return self._auditor.list(
            kind=p.get("kind"),
            include_open=bool(p.get("include_open", True)),
            limit=int(p.get("limit", 100)))

    def _rpc_recovery_stats(self, conn, p):
        return self._auditor.stats()

    def _rpc_doctor_report(self, conn, p):
        """Cross-plane correlation: one snapshot of all six planes ->
        ranked findings (metrics_history.build_doctor_report).  The
        assembly is a handful of in-process table reads — cheap enough
        for the CLI, the dashboard and the debug bundle to share."""
        from ray_tpu._private import metrics_history as mh
        p = p or {}
        snapshot = {
            "now": time.time(),
            "nodes": self._rpc_list_nodes(None, {}),
            "events": self._events_table.list(
                min_severity="WARNING",
                limit=int(p.get("events_limit", 200))),
            "episodes": self._auditor.list(
                limit=int(p.get("episodes_limit", 100))),
            "recovery_stats": self._auditor.stats(),
            "traces": self._span_table.list(slo_violations=True,
                                            limit=10),
            "dossiers": self._rpc_list_dossiers(None, {}),
            "history_stats": self._history.stats(),
        }
        return mh.build_doctor_report(snapshot)

    def _link_dossier_trace(self, dossier_id: str, trace_id: str) -> None:
        """A root span died carrying a dossier_id: stamp the trace id
        onto the dossier (prefix match like get_dossier) so forensics
        navigate both ways."""
        with self._lock:
            d = self._dossiers.get(dossier_id)
            if d is None and len(dossier_id) >= 8:
                d = next((cand for did, cand in self._dossiers.items()
                          if did.startswith(dossier_id)), None)
            if d is not None:
                d["trace_id"] = trace_id

    # ------------------------------------------------------------- dossiers
    def _rpc_put_dossier(self, conn, p):
        """Store a crash dossier (raylet harvest / GCS node-death
        assembly).  Bounded FIFO: forensic data for recent deaths, not
        an archive."""
        did = p["dossier_id"]
        dossier = dict(p.get("dossier") or {})
        dossier.setdefault("dossier_id", did)
        dossier.setdefault("ts", time.time())
        with self._lock:
            if did not in self._dossiers:
                self._dossier_order.append(did)
            self._dossiers[did] = dossier
            while len(self._dossiers) > CONFIG.gcs_max_dossiers and \
                    len(self._dossier_order) > 1:
                victim = self._dossier_order.popleft()
                if victim == did:   # never evict the one just stored
                    self._dossier_order.append(victim)
                    continue
                self._dossiers.pop(victim, None)
        return {"ok": True}

    def _rpc_get_dossier(self, conn, p):
        """Dossier by id — worker id hex (worker deaths; prefix match
        accepted) or node id hex (node deaths)."""
        want = p.get("dossier_id") or ""
        with self._lock:
            d = self._dossiers.get(want)
            if d is None and len(want) >= 8:
                for did, cand in self._dossiers.items():
                    if did.startswith(want):
                        d = cand
                        break
            return dict(d) if d else None

    def _rpc_list_dossiers(self, conn, p):
        with self._lock:
            return [{"dossier_id": did,
                     "kind": d.get("kind", "worker"),
                     "reason": d.get("reason", ""),
                     "node_id": d.get("node_id", ""),
                     "worker_id": d.get("worker_id", ""),
                     "ts": d.get("ts")}
                    for did, d in self._dossiers.items()]

    def _rpc_dump_stacks(self, conn, p):
        """Instantaneous per-thread stack dump + a short folded-stack
        sample of the GCS process itself (profiler plane)."""
        from ray_tpu._private.profiler import dump_stacks, sample_folded
        return {"threads": dump_stacks(),
                "folded": sample_folded(float((p or {}).get(
                    "duration", 0.2)))}

    # ------------------------------------------------------------------ rpc
    def _handle(self, conn: rpc.Connection, method: str, p: Any) -> Any:
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"GCS: unknown method {method}")
        out = fn(conn, p or {})
        hints = self._MUTATING_RPCS.get(method)
        if hints is not None:
            h = hints(p or {})
            if h is not self._SKIP_DURABILITY:
                self._mark_dirty(*h)
        return out

    def _on_disconnect(self, conn: rpc.Connection) -> None:
        with self._lock:
            for subs in self._subs.values():
                if conn in subs:
                    subs.remove(conn)
            dead_node = None
            for nid, c in list(self._node_conns.items()):
                if c is conn:
                    dead_node = nid
                    del self._node_conns[nid]
            # driver conn drop -> finish its job
            job_id = getattr(conn, "peer", None)
            if isinstance(job_id, str) and job_id in self._jobs:
                self._finish_job_locked(job_id)
        if dead_node:
            self._mark_node_dead(dead_node)

    # ----------------------------------------------------------------- nodes
    def _rpc_register_node(self, conn, p):
        node_id = p["node_id"]
        with self._lock:
            self._nodes[node_id] = {
                "node_id": node_id,
                "address": tuple(p["address"]),
                "store_path": p.get("store_path"),
                "resources": dict(p.get("resources", {})),
                "available": dict(p.get("resources", {})),
                "labels": dict(p.get("labels", {})),
                "alive": True,
                "last_heartbeat": time.monotonic(),
                "last_busy": time.monotonic(),
                "load": [],
            }
            self._node_conns[node_id] = conn
            conn.peer = ("node", node_id)
            self._cluster_scheduler.update_node(
                node_id, self._nodes[node_id]["resources"],
                self._nodes[node_id]["available"], True)
        self._publish("node", {"node_id": node_id, "state": "ALIVE"})
        self.record_event("INFO", "gcs", "NODE_UP",
                          f"node {node_id[:8]} registered",
                          node_id=node_id,
                          resources=dict(p.get("resources", {})))
        # a new node may unblock pending actors / placement groups
        threading.Thread(target=self._retry_pending_actors,
                         daemon=True).start()
        return {"ok": True}

    def _retry_pending_actors(self) -> None:
        with self._lock:
            # entries holding a retry_delay already have a backoff Timer
            # scheduled (resources-unavailable path) — re-dispatching them
            # here would defeat the backoff and hammer the full node
            pending = [aid for aid, a in self._actors.items()
                       if a["state"] in (PENDING_CREATION, RESTARTING)
                       and not a.get("dispatched")
                       and not a.get("retry_delay")]
            pending_pgs = [pgid for pgid, pg in self._placement_groups.items()
                           if pg["state"] == "PENDING"]
        for aid in pending:
            self._schedule_actor(aid)
        for pgid in pending_pgs:
            self._retry_placement_group(pgid)

    def _retry_placement_group(self, pgid: str) -> None:
        with self._lock:
            pg = self._placement_groups.get(pgid)
        if pg is None or pg["state"] != "PENDING":
            return
        self._try_place_pg(pg)

    # ------------------------------------------------- preemption / drain
    def _mark_node_draining(self, node_id: str, grace_s: float,
                            reason: str) -> bool:
        """Idempotently flag a node PREEMPTING: placement skips it and
        the typed event (with the grace deadline) fires exactly once
        per drain.  Returns False for unknown/dead nodes."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node["alive"]:
                return False
            already = bool(node.get("draining"))
            node["draining"] = True
            deadline = time.time() + grace_s
            if already:
                # a later notice can only shorten the advertised window
                # (and a passed-deadline heartbeat echoing remaining
                # grace 0 must not keep re-extending it)
                deadline = min(node.get("drain_deadline", deadline),
                               deadline)
            node["drain_deadline"] = deadline
        if not already:
            self.record_event(
                "WARNING", "gcs", "NODE_PREEMPTING",
                f"node {node_id[:8]} draining: {reason} "
                f"(grace {grace_s:.0f}s)", node_id=node_id,
                grace_s=grace_s, reason=reason,
                deadline=time.time() + grace_s)
            self._publish("node", {"node_id": node_id,
                                   "state": "DRAINING"})
        return True

    def _rpc_drain_node(self, conn, p):
        """Operator/provider-initiated drain (`ray-tpu drain`, spot
        preemption notice): mark the node draining and forward the
        drain to its raylet, which stops granting leases and evacuates
        primary copies (docs/fault_tolerance.md)."""
        node_id = p["node_id"]
        raw = p.get("grace_s")   # explicit 0 = die ASAP, keep it
        grace = CONFIG.drain_grace_s if raw is None else float(raw)
        reason = p.get("reason", "drain requested")
        if not self._mark_node_draining(node_id, grace, reason):
            return {"ok": False, "reason": "unknown or dead node"}
        with self._lock:
            node_conn = self._node_conns.get(node_id)
        if node_conn is not None:
            try:
                node_conn.call("drain", {"grace_s": grace,
                                         "reason": reason,
                                         "from_gcs": True}, timeout=10)
            except (ConnectionError, rpc.RpcError, TimeoutError) as e:
                return {"ok": True, "forwarded": False,
                        "reason": f"raylet drain forward failed: {e}"}
        return {"ok": True, "forwarded": node_conn is not None}

    def _rpc_report_node_draining(self, conn, p):
        """Raylet-initiated drain (the `drain` RPC hit the raylet
        directly): reflect it in the node table + event plane."""
        raw = p.get("grace_s")
        ok = self._mark_node_draining(
            p["node_id"],
            CONFIG.drain_grace_s if raw is None else float(raw),
            p.get("reason", "drain requested"))
        return {"ok": ok}

    def _rpc_report_node_drained(self, conn, p):
        """Drain completed: the raylet's evacuation ledger becomes the
        NODE_DRAINED event the chaos gate (and operators) assert on."""
        self.record_event(
            "INFO", "gcs", "NODE_DRAINED",
            f"node {p['node_id'][:8]} drained: "
            f"{p.get('evacuated', 0)} objects evacuated "
            f"({p.get('bytes', 0)} bytes, {p.get('failed', 0)} failed)",
            node_id=p["node_id"], evacuated=p.get("evacuated", 0),
            bytes=p.get("bytes", 0), failed=p.get("failed", 0),
            duration_s=p.get("duration_s"))
        return {"ok": True}

    def _rpc_report_object_evacuated(self, conn, p):
        """A draining raylet landed a copy of ``object_id`` on
        ``node_id``; owners consult this table when their location set
        empties (multi-source: every completed evacuation target joins
        the hint, so striped pulls can fan over them immediately)."""
        oid = p["object_id"]
        node = p["node_id"]
        with self._lock:
            rec = self._evac.pop(oid, None)
            nodes = rec[0] if rec is not None else set()
            nodes.add(node)
            # pop + reinsert rotates a refreshed hint to the back of
            # the insertion order, so the cap evicts the stalest entry
            self._evac[oid] = (nodes, time.monotonic())
            while len(self._evac) > CONFIG.gcs_max_evacuated_objects:
                self._evac.pop(next(iter(self._evac)))
        return {"ok": True}

    def _rpc_get_evacuated_locations(self, conn, p):
        """Batch lookup: {oid hex: [node hexes]} for ids with a live
        hint (unknown ids are simply absent from the reply)."""
        out = {}
        now = time.monotonic()
        ttl = CONFIG.gcs_evac_ttl_s
        with self._lock:
            for oid in p.get("object_ids", ()):
                rec = self._evac.get(oid)
                if rec is not None and now - rec[1] <= ttl:
                    out[oid] = sorted(rec[0])
        return out

    def _sweep_evac(self) -> None:
        now = time.monotonic()
        ttl = CONFIG.gcs_evac_ttl_s
        with self._lock:
            dead = [oid for oid, rec in self._evac.items()
                    if now - rec[1] > ttl]
            for oid in dead:
                self._evac.pop(oid, None)

    def _rpc_heartbeat(self, conn, p):
        with self._lock:
            node = self._nodes.get(p["node_id"])
            if node is None:
                return {"ok": False, "reregister": True}
            if not node["alive"]:
                # Death is permanent (reference semantics): a stalled node
                # whose actors were already restarted elsewhere must not be
                # resurrected — tell it to shut down.
                return {"ok": False, "dead": True}
            node["last_heartbeat"] = time.monotonic()
            # after a GCS restart the duplex conns died with the old
            # process: a heartbeat re-attaches this node's push channel
            if self._node_conns.get(p["node_id"]) is not conn:
                self._node_conns[p["node_id"]] = conn
                conn.peer = ("node", p["node_id"])
            node["available"] = dict(p.get("available", node["available"]))
            self._cluster_scheduler.update_node(
                p["node_id"], node["resources"], node["available"], True)
            node["load"] = list(p.get("load", []))
            busy = bool(p.get("busy"))
            if busy or node.get("busy"):
                node["last_busy"] = time.monotonic()
            node["busy"] = busy
            # heartbeat-carried drain flag: the idempotent backstop for
            # a raylet-initiated drain whose report RPC was lost
            hb_draining = bool(p.get("draining"))
            # bundle-pool reconciliation (docs/fault_tolerance.md):
            # the raylet reports the placement-group bundle pools it
            # holds; flag the ones the GCS no longer places on this
            # node (pg removed, or rescheduled elsewhere after a member
            # node died while this raylet was unreachable) so the
            # raylet can release the stranded reservation.  Only the
            # two unambiguous shapes are flagged — a PENDING group
            # mid-placement must keep its fresh reservations.
            stale_bundles = []
            for key in p.get("bundles", ()):
                pgid, _, idx = str(key).partition(":")
                pg = self._placement_groups.get(pgid)
                if pg is None:
                    stale_bundles.append(key)
                    continue
                placement = pg.get("placement")
                if pg.get("state") == "CREATED" and placement is not None:
                    try:
                        i = int(idx)
                    except ValueError:
                        continue
                    if i >= len(placement) or placement[i] != p["node_id"]:
                        stale_bundles.append(key)
            health = p.get("health")
            unhealthy_flip = None
            if health is not None:
                node["health"] = dict(health)
                reasons = self._health_reasons(health)
                was = bool(node.get("unhealthy"))
                now_bad = bool(reasons)
                node["unhealthy"] = now_bad
                node["unhealthy_reasons"] = reasons
                if now_bad != was:
                    unhealthy_flip = (now_bad, reasons, dict(health))
        if unhealthy_flip is not None:
            # edge-triggered: one event per transition, not per beat
            now_bad, reasons, health = unhealthy_flip
            self.record_event(
                "WARNING" if now_bad else "INFO", "gcs",
                "NODE_UNHEALTHY" if now_bad else "NODE_HEALTHY",
                f"node {p['node_id'][:8]} "
                + (f"unhealthy: {', '.join(reasons)}" if now_bad
                   else "recovered"),
                node_id=p["node_id"], **health)
        if hb_draining:
            # outside self._lock: _mark_node_draining takes it itself
            raw = p.get("drain_grace_s")
            self._mark_node_draining(
                p["node_id"],
                CONFIG.drain_grace_s if raw is None else float(raw),
                p.get("drain_reason") or "raylet-initiated drain")
        reply = {"ok": True}
        if stale_bundles:
            reply["stale_bundles"] = stale_bundles
        return reply

    @staticmethod
    def _health_reasons(health: dict) -> List[str]:
        """Threshold check over a raylet health snapshot -> list of
        breach descriptions ([] = healthy)."""
        reasons = []
        mem = health.get("mem_frac")
        if mem is not None and mem >= CONFIG.node_unhealthy_mem_frac:
            reasons.append(f"mem {mem:.0%}")
        store = health.get("store_frac")
        if store is not None and \
                store >= CONFIG.node_unhealthy_store_frac:
            reasons.append(f"store {store:.0%}")
        lag = health.get("loop_lag_ms")
        if lag is not None and lag >= CONFIG.node_unhealthy_lag_ms:
            reasons.append(f"loop lag {lag:.0f}ms")
        return reasons

    def _rpc_list_nodes(self, conn, p):
        now = time.monotonic()
        with self._lock:
            out = []
            for n in self._nodes.values():
                d = dict(n)
                d["idle_s"] = now - n.get("last_busy", now)
                out.append(d)
            return out

    def _prune_stale_metrics(self, now: Optional[float] = None) -> int:
        """Delete RUNTIME metrics/ KV entries whose payload ts is
        stale: the publishing process is gone (or wedged), and its
        frozen last snapshot must not haunt /metrics and list_metrics
        forever.  Only payloads self-marked ``runtime`` are eligible —
        runtime flushers keep-alive their ts even when idle, so
        staleness means death; user metrics (util/metrics.py) flush on
        record only, and an idle live process's once-set gauge must
        not be swept."""
        import json as _json
        from ray_tpu._private.runtime_metrics import METRICS_STALE_AFTER_S
        now = time.time() if now is None else now
        pruned = 0
        with self._lock:
            for key in [k for k in self._kv if k.startswith("metrics/")]:
                try:
                    blob = _json.loads(self._kv[key])
                    ts = blob.get("ts")
                    swept = bool(blob.get("runtime"))
                except (ValueError, TypeError, AttributeError):
                    continue
                if swept and (ts is None
                              or now - ts > METRICS_STALE_AFTER_S):
                    del self._kv[key]
                    pruned += 1
        return pruned

    def _health_loop(self) -> None:
        period = CONFIG.heartbeat_period_ms / 1000.0
        threshold = CONFIG.health_check_failure_threshold
        ticks = 0
        while not self._stopped.wait(period):
            now = time.monotonic()
            dead = []
            with self._lock:
                for nid, node in self._nodes.items():
                    if node["alive"] and \
                            now - node["last_heartbeat"] > period * threshold:
                        dead.append(nid)
                have_pending = any(
                    a["state"] in (PENDING_CREATION, RESTARTING)
                    and not a.get("dispatched")
                    for a in self._actors.values()) or any(
                    pg["state"] == "PENDING"
                    for pg in self._placement_groups.values())
            for nid in dead:
                self._mark_node_dead(nid)
            # dead processes leave their last metrics snapshot behind in
            # the KV; sweep keys whose payload ts went stale (live
            # flushers refresh ts every few intervals) so /metrics and
            # list_metrics don't report frozen gauges forever and KV
            # cardinality stays bounded under worker churn
            if ticks % 50 == 0:
                self._prune_stale_metrics()
                self._sweep_evac()
            # actors/pgs parked with "no feasible node" are otherwise only
            # retried on node registration — also retry as resources free
            # up (freshly reported by heartbeats), else a full-but-draining
            # cluster livelocks pending actors forever.  Off-thread: a
            # create_actor dispatch can block for actor_creation_timeout_s
            # and must not stall dead-node detection.
            ticks += 1
            if have_pending and ticks % 2 == 0 and \
                    not self._retry_inflight.is_set():
                self._retry_inflight.set()

                def _retry_and_clear():
                    try:
                        self._retry_pending_actors()
                    finally:
                        self._retry_inflight.clear()
                threading.Thread(target=_retry_and_clear,
                                 daemon=True).start()

    def _mark_node_dead(self, node_id: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if not node or not node["alive"]:
                return
            node["alive"] = False
            self._cluster_scheduler.remove_node(node_id)
            affected = [aid for aid, a in self._actors.items()
                        if a.get("node_id") == node_id
                        and a["state"] in (ALIVE, PENDING_CREATION)]
            broken_pgs = [pg for pg in self._placement_groups.values()
                          if pg.get("placement") and
                          node_id in pg["placement"]]
        logger.warning("node %s marked dead (actors affected: %d)",
                       node_id[:8], len(affected))
        self._mark_dirty(("_nodes", node_id))
        self._publish("node", {"node_id": node_id, "state": "DEAD"})
        self.record_event("ERROR", "gcs", "NODE_DEAD",
                          f"node {node_id[:8]} missed "
                          f"{CONFIG.health_check_failure_threshold} "
                          "heartbeats", node_id=node_id,
                          actors_affected=len(affected))
        # node-death dossier: the raylet can't harvest its own corpse,
        # so the GCS assembles what it already holds — the node's last
        # flushed events, health snapshot and heartbeat age — under the
        # node id, driver-retrievable like any worker dossier
        self._rpc_put_dossier(None, {
            "dossier_id": node_id,
            "dossier": {
                "kind": "node", "node_id": node_id,
                "reason": f"missed "
                          f"{CONFIG.health_check_failure_threshold} "
                          f"heartbeats",
                "health": node.get("health"),
                "last_heartbeat_age_s": round(
                    time.monotonic() - node.get("last_heartbeat", 0), 3),
                "actors_affected": len(affected),
                "events": self._events_table.list(node_id=node_id,
                                                  limit=100),
            }})
        for aid in affected:
            self._on_actor_failure(aid, f"node {node_id[:8]} died")
        # placement groups with a bundle on the dead node go back to PENDING
        # and get fully re-reserved (reference: rescheduling state). Runs on
        # its own thread: the return_bundle/reserve_bundle RPCs must not
        # stall the health loop's detection of other dead nodes.
        if broken_pgs:
            threading.Thread(target=self._reschedule_broken_pgs,
                             args=(broken_pgs, node_id), daemon=True).start()

    def _reschedule_broken_pgs(self, broken_pgs, node_id: str) -> None:
        for pg in broken_pgs:
            with self._lock:
                if self._placement_groups.get(pg["pg_id"]) is not pg:
                    continue   # removed concurrently; must not resurrect
                placement = pg["placement"] or []
                conns = {nid: self._node_conns.get(nid)
                         for nid in placement if nid != node_id}
                pg["state"] = "PENDING"
                pg["placement"] = None
            for i, nid in enumerate(placement):
                node_conn = conns.get(nid)
                if node_conn is None:
                    continue
                try:
                    node_conn.call("return_bundle",
                                   {"pg_id": pg["pg_id"], "index": i},
                                   timeout=10)
                except (ConnectionError, rpc.RpcError, TimeoutError):
                    pass
            self._publish("placement_group",
                          {"pg_id": pg["pg_id"], "state": "PENDING"})
            self._try_place_pg(pg)

    # ----------------------------------------------------------------- jobs
    def _rpc_register_job(self, conn, p):
        job_id = p["job_id"]
        with self._lock:
            if job_id not in self._jobs:
                self._jobs[job_id] = {
                    "job_id": job_id, "state": "RUNNING",
                    "driver_address": tuple(p.get("driver_address") or ()),
                    "start_time": time.time(),
                    "entrypoint": p.get("entrypoint", "")}
            # idempotent re-register (e.g. after a GCS restart) must still
            # bind this connection to the job for disconnect cleanup
            conn.peer = job_id
        return {"ok": True}

    def _rpc_finish_job(self, conn, p):
        with self._lock:
            self._finish_job_locked(p["job_id"])
        return {"ok": True}

    def _finish_job_locked(self, job_id: str) -> None:
        job = self._jobs.get(job_id)
        if job and job["state"] == "RUNNING":
            job["state"] = "FINISHED"
            job["end_time"] = time.time()
            # non-detached actors of the job die with it — and their worker
            # processes must actually be killed so their lease resources free
            doomed = []
            for aid, a in self._actors.items():
                if a.get("job_id") == job_id and not a.get("detached") \
                        and a["state"] != DEAD:
                    a["state"] = DEAD
                    a["death_cause"] = "job finished"
                    node_conn = self._node_conns.get(a.get("node_id") or "")
                    doomed.append((aid, node_conn))
                    if a.get("name"):
                        self._named_actors.pop(
                            (a.get("namespace", ""), a["name"]), None)
            for aid, node_conn in doomed:
                if node_conn is not None:
                    try:
                        node_conn.push("kill_actor_worker", {"actor_id": aid})
                    except ConnectionError:
                        pass
            self._publish("job", {"job_id": job_id, "state": "FINISHED"})
            # per-actor hints keep the WAL record O(affected), not a
            # whole-table pickle under the global lock (_named_actors is
            # a handful of entries — whole-table is fine there)
            self._mark_dirty(("_jobs", job_id),
                             *((("_actors", aid) for aid, _ in doomed)),
                             ("_named_actors", None))

    def _rpc_list_jobs(self, conn, p):
        with self._lock:
            return [dict(j) for j in self._jobs.values()]

    # ----------------------------------------------------------- task events
    def _rpc_task_events_put(self, conn, p):
        """Workers flush TaskEventBuffer batches here (cf. reference
        TaskInfoGcsService.AddTaskEventData, gcs_service.proto:635)."""
        return {"dropped": self._task_table.put_events(p["events"])}

    def _rpc_list_task_events(self, conn, p):
        return self._task_table.list(
            job_id=p.get("job_id"), state=p.get("state"),
            name=p.get("name"), limit=int(p.get("limit", 10000)))

    # ------------------------------------------------------------------- kv
    def _metrics_kv_put(self, key: str, value: bytes) -> None:
        """Runtime-metrics flusher sink: plain KV write, never WALed."""
        with self._lock:
            self._kv[key] = value
        from ray_tpu._private import metrics_history as mh
        if mh.history_on():
            self._history.ingest(key, value)

    def _rpc_kv_put(self, conn, p):
        with self._lock:
            existed = p["key"] in self._kv
            if p.get("overwrite", True) or not existed:
                self._kv[p["key"]] = p["value"]
        # worker metrics flushers arrive over this generic RPC (their
        # sink is a kv_put call): stage them for the history plane too
        # (batched fold — the RPC reply never waits on ring work)
        if p["key"].startswith("metrics/"):
            from ray_tpu._private import metrics_history as mh
            if mh.history_on():
                self._history.ingest(p["key"], p["value"])
        return {"existed": existed}

    def _rpc_kv_get(self, conn, p):
        with self._lock:
            return self._kv.get(p["key"])

    def _rpc_kv_del(self, conn, p):
        with self._lock:
            return {"deleted": self._kv.pop(p["key"], None) is not None}

    def _rpc_kv_keys(self, conn, p):
        prefix = p.get("prefix", "")
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    def _rpc_kv_exists(self, conn, p):
        with self._lock:
            return p["key"] in self._kv

    # --------------------------------------------------------------- pubsub
    def _rpc_subscribe(self, conn, p):
        with self._lock:
            self._subs.setdefault(p["channel"], []).append(conn)
        return {"ok": True}

    def _rpc_publish(self, conn, p):
        self._publish(p["channel"], p["message"])
        return {"ok": True}

    def _publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, []))
        for c in subs:
            try:
                c.push("pubsub", {"channel": channel, "message": message})
            except ConnectionError:
                pass

    # --------------------------------------------------------------- actors
    def _rpc_register_actor(self, conn, p):
        """Register + schedule an actor; cf. GcsActorManager::HandleRegisterActor
        (/root/reference/src/ray/gcs/gcs_server/gcs_actor_manager.cc:240) and
        GcsActorScheduler (gcs_actor_scheduler.h:111)."""
        aid = p["actor_id"]
        with self._lock:
            if aid in self._actors:
                return dict(self._actors[aid])
            name = p.get("name")
            ns = p.get("namespace", "")
            if name and (ns, name) in self._named_actors:
                raise ValueError(f"actor name {name!r} already taken")
            entry = {
                "actor_id": aid,
                "caller_node_id": p.get("caller_node_id"),
                "job_id": p.get("job_id"),
                "name": name,
                "namespace": ns,
                "detached": bool(p.get("detached")),
                "state": PENDING_CREATION,
                "spec": p["spec"],          # opaque creation task spec bytes
                "resources": dict(p.get("resources", {})),
                "max_restarts": int(p.get("max_restarts", 0)),
                "restarts": 0,
                "node_id": None,
                "address": None,
                "death_cause": None,
                "bundle": p.get("bundle"),  # [pg_id_hex, index] or None
                "strategy": p.get("strategy"),  # node_affinity/spread dict
                "language": p.get("language"),  # None/python, or "cpp"
                "runtime_env": p.get("runtime_env"),
            }
            self._actors[aid] = entry
            if name:
                self._named_actors[(ns, name)] = aid
        # dispatch asynchronously: Actor.remote() must return immediately
        # even if __init__ blocks (e.g. on a collective rendezvous with
        # peers created later) — reference semantics: GcsActorManager
        # schedules out-of-band, clients poll actor state.
        threading.Thread(target=self._schedule_actor, args=(aid,),
                         daemon=True).start()
        return {"ok": True}

    def _schedule_actor(self, aid: str) -> None:
        with self._lock:
            entry = self._actors.get(aid)
            if entry is None or entry["state"] == DEAD \
                    or entry.get("dispatched"):
                return
            need = entry["resources"]
            bundle = entry.get("bundle")
            strategy = entry.get("strategy") or {}
            fail_reason = None
            # candidates: [(node_id, bundle_or_None), ...] tried in order
            candidates = []
            if bundle is not None:
                # actor is pinned to a placement-group bundle: it must land
                # on the node holding that reserved bundle
                pg = self._placement_groups.get(bundle[0])
                if pg is None:
                    fail_reason = \
                        f"placement group {bundle[0][:8]} removed"
                elif pg["state"] != "CREATED":
                    logger.info("actor %s pending: placement group pending",
                                aid[:8])
                    entry.pop("retry_delay", None)
                    return
                else:
                    idx = int(bundle[1])
                    placement = pg["placement"]
                    if idx >= len(placement) or idx < -1:
                        fail_reason = (
                            f"bundle index {idx} out of range for "
                            f"{len(placement)}-bundle placement group")
                    else:
                        indices = [idx] if idx >= 0 \
                            else list(range(len(placement)))
                        # an actor asking more than its bundle reserves can
                        # never be placed — fail instead of retrying forever
                        specs = pg["bundles"]
                        fits = [i for i in indices
                                if all(specs[i].get(r, 0) >= v
                                       for r, v in need.items())]
                        if not fits:
                            fail_reason = (
                                f"actor requires {need} but no bundle of "
                                f"placement group {bundle[0][:8]} reserves "
                                "that much")
                        for i in fits:
                            node = self._nodes.get(placement[i])
                            if node is not None and node["alive"]:
                                candidates.append(
                                    (node["node_id"], [bundle[0], i]))
                        if not candidates and fail_reason is None:
                            entry.pop("retry_delay", None)
                            return  # bundle nodes gone; pg will reschedule
            elif strategy.get("type") == "node_affinity":
                node = self._nodes.get(strategy["node_id"])
                if node is not None and node["alive"]:
                    candidates.append((node["node_id"], None))
                elif not strategy.get("soft"):
                    fail_reason = (
                        f"node {strategy['node_id'][:8]} not found/alive "
                        "(hard node affinity)")
                if not candidates and fail_reason is None:
                    # soft affinity falls back to the default policy
                    strategy = {}
            if not candidates and fail_reason is None and bundle is None \
                    and strategy.get("type") != "node_affinity":
                def _fits(node):
                    # milli-unit rounding to match the scheduler's fixed-
                    # point arithmetic (csrc/scheduler.cc) exactly
                    return all(
                        int(round(node["available"].get(r, 0) * 1000))
                        >= int(round(v * 1000)) for r, v in need.items())
                # draining nodes are about to disappear: placing new
                # actors there guarantees an immediate restart
                feasible = [node for node in self._nodes.values()
                            if node["alive"] and not node.get("draining")
                            and _fits(node)]
                spread = strategy.get("type") == "spread"
                if spread:
                    # most-available-CPU first (cf. SpreadSchedulingPolicy)
                    feasible.sort(
                        key=lambda n: -n["available"].get("CPU", 0))
                elif len(feasible) > 1:
                    # rank the primary choice with the native hybrid policy
                    # (csrc/scheduler.cc; cf. hybrid_scheduling_policy.h:48):
                    # pack near the creator until it crosses the spill
                    # threshold; remaining feasible nodes stay as fallbacks
                    best = self._cluster_scheduler.best_node(
                        need, local_id=entry.get("caller_node_id"))
                    if best is not None:
                        feasible.sort(
                            key=lambda n: n["node_id"] != best)
                for node in feasible:
                    candidates.append((node["node_id"], None))
            if fail_reason is None and not candidates:
                # no feasible node now; retried on the next node registration
                # (kept pending even if infeasible against total capacity —
                # the autoscaler scales from pending demand — but say which)
                if not self._cluster_scheduler.feasible_anywhere(need):
                    logger.warning(
                        "actor %s pending: infeasible with current cluster "
                        "total resources (%s); waiting for the cluster to "
                        "grow", aid[:8], need)
                else:
                    logger.info("actor %s pending: no feasible node", aid[:8])
                # hand the entry back to _retry_pending_actors (a stale
                # retry_delay would park it forever: nothing else retries)
                entry.pop("retry_delay", None)
                return
            if fail_reason is None:
                entry["dispatched"] = True
        if fail_reason is not None:
            self._on_actor_failure(aid, fail_reason)
            return
        last_err = None
        for node_id, cand_bundle in candidates:
            with self._lock:
                entry["node_id"] = node_id
                node_conn = self._node_conns.get(node_id)
            if node_conn is None:
                last_err = f"no connection to node {node_id[:8]}"
                continue
            try:
                node_conn.call("create_actor", {
                    "actor_id": aid,
                    "spec": entry["spec"],
                    "resources": entry["resources"],
                    "bundle": cand_bundle,
                    "runtime_env": entry.get("runtime_env"),
                    "language": entry.get("language"),
                }, timeout=CONFIG.actor_creation_timeout_s)
                with self._lock:
                    entry.pop("retry_delay", None)
                    killed_mid_flight = entry["state"] == DEAD
                if killed_mid_flight:
                    # kill_actor raced this dispatch: the kill push found
                    # nothing on the node yet, so the worker+resources it
                    # just acquired would leak without this reap
                    try:
                        node_conn.push("kill_actor_worker",
                                       {"actor_id": aid})
                    except ConnectionError:
                        pass
                return
            except (rpc.RemoteError, ConnectionError, TimeoutError) as e:
                last_err = e
                # only a resource shortfall is worth trying elsewhere; a
                # user __init__ error would just re-raise on every node
                if isinstance(e, rpc.RemoteError) and \
                        "resources unavailable" not in str(e):
                    break
                continue
        if isinstance(last_err, rpc.RemoteError) and \
                "resources unavailable" in str(last_err):
            # candidate node(s) alive but momentarily out of resources
            # (pinned affinity/bundle): park the actor pending and retry
            # with backoff, like the no-feasible-node path, instead of
            # failing it
            logger.info("actor %s pending: %s", aid[:8], last_err)
            with self._lock:
                entry["dispatched"] = False
                entry["node_id"] = None
                delay = entry.get("retry_delay", 0.2)
                entry["retry_delay"] = min(delay * 2, 5.0)
                if strategy.get("type") == "node_affinity" \
                        and strategy.get("soft"):
                    # soft affinity: the pinned node is full — fall back to
                    # the default policy rather than hammering that node
                    entry["strategy"] = None
            timer = threading.Timer(delay, self._schedule_actor, args=(aid,))
            timer.daemon = True
            timer.start()
            return
        reason = repr(last_err) if last_err is not None else "no candidates"
        logger.warning("actor %s creation dispatch failed: %s",
                       aid[:8], reason)
        self._on_actor_failure(aid, f"creation failed: {reason}")

    def _rpc_actor_ready(self, conn, p):
        """Called by the actor's worker once __init__ completed."""
        with self._lock:
            entry = self._actors.get(p["actor_id"])
            if entry is None:
                return {"ok": False}
            dead = entry["state"] == DEAD
            if dead:
                # killed while __init__ ran: reap instead of resurrecting
                node_conn = self._node_conns.get(entry.get("node_id") or "")
            else:
                entry["state"] = ALIVE
                entry["address"] = tuple(p["address"])
                # a successful restart voids the previous crash's
                # dossier reference — the next death names its own
                entry.pop("death_worker_id", None)
        if dead:
            if node_conn is not None:
                try:
                    node_conn.push("kill_actor_worker",
                                   {"actor_id": p["actor_id"]})
                except ConnectionError:
                    pass
            return {"ok": False, "dead": True}
        self._publish("actor", {"actor_id": p["actor_id"], "state": ALIVE,
                                "address": tuple(p["address"])})
        return {"ok": True}

    def _rpc_actor_failed(self, conn, p):
        self._on_actor_failure(p["actor_id"], p.get("reason", "worker died"),
                               worker_id=p.get("worker_id"))
        return {"ok": True}

    def _on_actor_failure(self, aid: str, reason: str,
                          worker_id: Optional[str] = None) -> None:
        """Actor restart FSM; cf. GcsActorManager::OnActorCreationFailed /
        SchedulePendingActors (gcs_actor_manager.cc:1233)."""
        with self._lock:
            entry = self._actors.get(aid)
            if entry is None:
                return
            if worker_id:
                # the worker whose death caused (or followed — a
                # kill_actor marks DEAD before the raylet reports the
                # worker's exit) this transition: the handle that
                # points ActorDiedError.debug_dossier() at the dossier.
                # Overwrite while the actor is live (each failure's
                # worker supersedes the last restart's); once DEAD,
                # first writer wins — a late duplicate report must not
                # repoint an already-propagated reference.
                if entry["state"] != DEAD or \
                        not entry.get("death_worker_id"):
                    entry["death_worker_id"] = worker_id
            if entry["state"] == DEAD:
                return
            if entry["restarts"] < entry["max_restarts"]:
                entry["restarts"] += 1
                entry["state"] = RESTARTING
                entry["address"] = None
                entry["dispatched"] = False
                restart = True
            else:
                entry["state"] = DEAD
                entry["death_cause"] = reason
                restart = False
        # dirty AFTER the state transition: marking first lets the snapshot
        # tick clear the flag and persist the pre-transition tables
        self._mark_dirty(("_actors", aid))
        self._publish("actor", {"actor_id": aid,
                                "state": RESTARTING if restart else DEAD,
                                "reason": reason})
        self.record_event("WARNING" if restart else "ERROR", "gcs",
                          "ACTOR_RESTARTING" if restart else "ACTOR_DEAD",
                          f"actor {aid[:8]}: {reason}", actor_id=aid,
                          worker_id=worker_id)
        if restart:
            logger.info("restarting actor %s (%s)", aid[:8], reason)
            self._schedule_actor(aid)

    def _rpc_get_actor(self, conn, p):
        aid = p.get("actor_id")
        with self._lock:
            if aid is None:
                key = (p.get("namespace", ""), p["name"])
                aid = self._named_actors.get(key)
                if aid is None:
                    return None
            entry = self._actors.get(aid)
            return dict(entry, spec=None) if entry else None

    def _rpc_list_actors(self, conn, p):
        with self._lock:
            return [dict(a, spec=None) for a in self._actors.values()]

    def _rpc_kill_actor(self, conn, p):
        aid = p["actor_id"]
        with self._lock:
            entry = self._actors.get(aid)
            if entry is None:
                return {"ok": False}
            entry["state"] = DEAD
            entry["death_cause"] = "killed via kill_actor"
            entry["max_restarts"] = 0
            addr = entry.get("address")
            node_conn = self._node_conns.get(entry.get("node_id") or "")
            if entry.get("name"):
                self._named_actors.pop(
                    (entry.get("namespace", ""), entry["name"]), None)
        if node_conn is not None:
            try:
                node_conn.push("kill_actor_worker", {"actor_id": aid})
            except ConnectionError:
                pass
        self._publish("actor", {"actor_id": aid, "state": DEAD,
                                "reason": "killed"})
        return {"ok": True, "address": addr}

    # ----------------------------------------------------- placement groups
    def _rpc_create_placement_group(self, conn, p):
        """Register a placement group and try to place it now; otherwise it
        stays PENDING and is retried as nodes join (cf. reference
        GcsPlacementGroupManager / GcsPlacementGroupScheduler 2PC)."""
        pgid = p["pg_id"]
        pg = {
            "pg_id": pgid, "state": "PENDING", "bundles": p["bundles"],
            "strategy": p.get("strategy", "PACK"),
            "name": p.get("name", ""), "placement": None,
            "job_id": p.get("job_id"),
        }
        with self._lock:
            existing = self._placement_groups.get(pgid)
            if existing is not None:
                return {"state": existing["state"]}
            self._placement_groups[pgid] = pg
        self._try_place_pg(pg)
        return {"state": pg["state"], "placement": pg["placement"]}

    def _try_place_pg(self, pg) -> bool:
        """Plan a placement, then 2-phase reserve the bundles on the chosen
        raylets (reserve_bundle; rollback with return_bundle on failure)."""
        pgid = pg["pg_id"]
        with self._lock:
            if pg["state"] != "PENDING" or pg.get("placing"):
                return pg["state"] == "CREATED"
            if self._placement_groups.get(pgid) is not pg:
                return False   # removed (or re-registered) concurrently
            nodes = [n for n in self._nodes.values()
                     if n["alive"] and not n.get("draining")]
            placement = self._pack_bundles(pg["bundles"], pg["strategy"],
                                           nodes)
            if placement is None:
                return False
            # single in-flight placer per group: concurrent attempts (client
            # RPC vs node-registration retry) would double-reserve bundles
            pg["placing"] = True
            # optimistic deduction on the GCS view so concurrent planners
            # don't double-book; raylet heartbeats reconcile it afterwards
            for bundle, node_id in zip(pg["bundles"], placement):
                node = self._nodes[node_id]
                for r, v in bundle.items():
                    node["available"][r] = node["available"].get(r, 0) - v
            conns = {nid: self._node_conns.get(nid) for nid in placement}
        try:
            return self._reserve_pg_bundles(pg, placement, conns)
        finally:
            with self._lock:
                pg["placing"] = False
            # after the transition so the snapshot can't persist pre-state
            self._mark_dirty(("_placement_groups", pg["pg_id"]))

    def _reserve_pg_bundles(self, pg, placement, conns) -> bool:
        pgid = pg["pg_id"]
        reserved = []
        failed = False
        for i, (bundle, nid) in enumerate(zip(pg["bundles"], placement)):
            node_conn = conns.get(nid)
            ok = False
            if node_conn is not None:
                try:
                    reply = node_conn.call(
                        "reserve_bundle",
                        {"pg_id": pgid, "index": i, "resources": bundle},
                        timeout=10)
                    ok = bool(reply and reply.get("ok"))
                except (ConnectionError, rpc.RpcError, TimeoutError):
                    ok = False
            if not ok:
                failed = True
                break
            reserved.append((i, nid))
        if failed:
            for i, nid in reserved:
                node_conn = conns.get(nid)
                if node_conn is None:
                    continue
                try:
                    node_conn.call("return_bundle",
                                   {"pg_id": pgid, "index": i}, timeout=10)
                except (ConnectionError, rpc.RpcError, TimeoutError):
                    pass
            with self._lock:  # roll back the optimistic view deduction
                for bundle, node_id in zip(pg["bundles"], placement):
                    node = self._nodes.get(node_id)
                    if node and node["alive"]:
                        for r, v in bundle.items():
                            node["available"][r] = \
                                node["available"].get(r, 0) + v
            return False
        with self._lock:
            if self._placement_groups.get(pgid) is not pg:
                removed_during_placement = True
            else:
                removed_during_placement = False
                pg["state"] = "CREATED"
                pg["placement"] = placement
        if removed_during_placement:
            # remove_placement_group won the race: release what we reserved
            for i, nid in reserved:
                node_conn = conns.get(nid)
                if node_conn is None:
                    continue
                try:
                    node_conn.call("return_bundle",
                                   {"pg_id": pgid, "index": i}, timeout=10)
                except (ConnectionError, rpc.RpcError, TimeoutError):
                    pass
            return False
        self._publish("placement_group", {"pg_id": pgid, "state": "CREATED"})
        # actors parked on this group's bundles can now be scheduled
        with self._lock:
            parked = [aid for aid, a in self._actors.items()
                      if a.get("bundle") and a["bundle"][0] == pgid
                      and a["state"] in (PENDING_CREATION, RESTARTING)
                      and not a.get("dispatched")]
        for aid in parked:
            self._schedule_actor(aid)
        return True

    def _pack_bundles(self, bundles, strategy, nodes) -> Optional[List[str]]:
        """Bin-pack bundles onto nodes. TPU-slice awareness: if any bundle
        names a ``tpu-slice`` resource, candidate nodes are restricted to a
        single slice (node label ``tpu-slice``) so the group is atomic on
        one pod slice (SURVEY.md §2.6)."""
        slice_bundles = any("tpu-slice" in b for b in bundles)
        if slice_bundles:
            slices: Dict[str, List[dict]] = {}
            for n in nodes:
                label = n.get("labels", {}).get("tpu-slice")
                if label:
                    slices.setdefault(label, []).append(n)
            for _, group in sorted(slices.items()):
                placement = self._pack_bundles_on(bundles, strategy, group)
                if placement is not None:
                    return placement
            return None
        return self._pack_bundles_on(bundles, strategy, nodes)

    def _pack_bundles_on(self, bundles, strategy, nodes
                         ) -> Optional[List[str]]:
        avail = {n["node_id"]: dict(n["available"]) for n in nodes}
        order = list(avail.keys())
        placement = []
        for bundle in bundles:
            placed = None
            candidates = order if strategy in ("PACK", "STRICT_PACK") \
                else sorted(order, key=lambda nid: -min(
                    avail[nid].get(r, 0) for r in bundle) if bundle else 0)
            for nid in candidates:
                if all(avail[nid].get(r, 0) >= v for r, v in bundle.items()):
                    placed = nid
                    break
            if placed is None:
                return None
            if strategy == "STRICT_PACK" and placement and \
                    placed != placement[0]:
                return None
            for r, v in bundle.items():
                avail[placed][r] -= v
            placement.append(placed)
        if strategy == "STRICT_SPREAD" and \
                len(set(placement)) != len(placement):
            return None
        return placement

    def _rpc_get_placement_group(self, conn, p):
        with self._lock:
            pg = self._placement_groups.get(p["pg_id"])
            return dict(pg) if pg else None

    def _rpc_list_placement_groups(self, conn, p):
        with self._lock:
            return {pgid: dict(pg)
                    for pgid, pg in self._placement_groups.items()}

    def _rpc_remove_placement_group(self, conn, p):
        pgid = p["pg_id"]
        with self._lock:
            pg = self._placement_groups.pop(pgid, None)
            if pg is None:
                return {"ok": False}
            placement = pg.get("placement") or []
            conns = {nid: self._node_conns.get(nid) for nid in placement}
            # actors living in (or parked on) this group die with it
            # (reference semantics: GcsPlacementGroupManager kills actors
            # of removed groups)
            doomed = [
                (aid, self._node_conns.get(a.get("node_id") or ""))
                for aid, a in self._actors.items()
                if a.get("bundle") and a["bundle"][0] == pgid
                and a["state"] != DEAD]
        for aid, node_conn in doomed:
            if node_conn is not None:
                try:
                    node_conn.push("kill_actor_worker", {"actor_id": aid})
                except ConnectionError:
                    pass
            self._on_actor_failure(aid, "placement group removed")
        for i, nid in enumerate(placement):
            node_conn = conns.get(nid)
            if node_conn is None:
                continue
            try:
                node_conn.call("return_bundle",
                               {"pg_id": pgid, "index": i}, timeout=10)
            except (ConnectionError, rpc.RpcError, TimeoutError):
                pass
        self._publish("placement_group",
                      {"pg_id": pgid, "state": "REMOVED"})
        return {"ok": True}


class GcsClient:
    """Thin client; one duplex connection, also carries pubsub pushes.

    Transport failures trigger transparent reconnects (bounded by the call
    timeout) so clients ride through a GCS restart — the reference's
    gcs_rpc_client reconnection/backoff behavior.  Subscriptions are
    replayed on the fresh connection."""

    def __init__(self, address: Tuple[str, int],
                 push_handler=None, timeout: Optional[float] = None,
                 handler=None, connect_retry: bool = False):
        self._address = tuple(address)
        self._timeout = timeout or CONFIG.gcs_rpc_timeout_s
        self._sub_lock = threading.Lock()
        self._sub_handlers: Dict[str, List] = {}
        self._user_push = push_handler
        self._handler = handler
        self._conn_lock = threading.Lock()
        self._closed = False
        # called with this client after a successful reconnect, so owners
        # of identity state (e.g. the driver's job binding) can restore it
        self.on_reconnect = None
        # ``handler`` serves requests the GCS sends *to us* over this duplex
        # connection (e.g. create_actor dispatched to a raylet).
        # ``connect_retry`` (daemon call sites only — raylet, dashboard,
        # monitor): the FIRST connect retries with bounded backoff,
        # because a freshly spawned daemon races the GCS's accept loop
        # under box load — the address file is published once the
        # socket listens, but a loaded host can starve the acceptor
        # long enough for a connect burst to be refused.  One refused
        # connect must not kill the raylet at spawn (the load-dependent
        # startup-race flake); the window is daemon_connect_retry_s.
        # Interactive clients (init(address=...), the CLI) keep
        # fail-fast semantics: a dead or mistyped address raises
        # immediately.
        if connect_retry:
            self._conn = self._connect_with_retry(handler)
        else:
            self._conn = rpc.connect(self._address,
                                     push_handler=self._on_push,
                                     handler=handler)

    def _connect_with_retry(self, handler) -> rpc.Connection:
        deadline = time.monotonic() + CONFIG.daemon_connect_retry_s
        delay = 0.05
        while True:
            try:
                return rpc.connect(self._address,
                                   push_handler=self._on_push,
                                   handler=handler)
            except ConnectionError:
                # ConnectionError only: a resolver failure (gaierror, a
                # mistyped host) can never heal and must fail fast
                if time.monotonic() >= deadline:
                    raise
                time.sleep(min(delay, max(0.0,
                                          deadline - time.monotonic())))
                delay = min(delay * 2, 1.0)

    def _on_push(self, method: str, payload: Any) -> None:
        if method == "pubsub":
            channel = payload["channel"]
            with self._sub_lock:
                handlers = list(self._sub_handlers.get(channel, []))
            for h in handlers:
                try:
                    h(payload["message"])
                except Exception:
                    logger.exception("pubsub handler error on %s", channel)
        elif self._user_push is not None:
            self._user_push(method, payload)

    def _reconnect(self) -> None:
        with self._conn_lock:
            if self._closed or not self._conn.closed:
                return
            conn = rpc.connect(self._address, push_handler=self._on_push,
                               handler=self._handler)
            with self._sub_lock:
                channels = list(self._sub_handlers)
            for channel in channels:
                conn.call("subscribe", {"channel": channel},
                          timeout=self._timeout)
            self._conn = conn
            logger.info("GCS connection re-established to %s", self._address)
        if self.on_reconnect is not None:
            try:
                self.on_reconnect(self)
            except Exception:
                logger.warning("GCS on_reconnect callback failed",
                               exc_info=True)

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None) -> Any:
        t = timeout or self._timeout
        deadline = None if t is None else time.monotonic() + t
        while True:
            conn = self._conn
            try:
                if conn.closed:
                    raise ConnectionError("GCS connection closed")
                return conn.call(method, payload, timeout=t)
            except (ConnectionError, OSError):
                if self._closed or (deadline is not None
                                    and time.monotonic() >= deadline):
                    raise
                # A send-side OSError can surface as ConnectionError with
                # the conn not yet marked closed (the reader thread closes
                # it asynchronously); close it ourselves so _reconnect
                # actually reconnects instead of no-opping, and so we
                # don't busy-spin on the broken socket. NOTE: retrying
                # re-sends RPCs that may already have been applied
                # server-side — every GCS mutating RPC must stay
                # idempotent (they key on caller-chosen ids, not counters).
                try:
                    conn.close()
                except Exception:
                    pass
                try:
                    self._reconnect()
                except (ConnectionError, OSError, rpc.RpcError,
                        TimeoutError):
                    pass
                if self._conn.closed:
                    time.sleep(0.2)

    def subscribe(self, channel: str, handler) -> None:
        with self._sub_lock:
            self._sub_handlers.setdefault(channel, []).append(handler)
        self.call("subscribe", {"channel": channel})

    # convenience KV API (cf. reference internal_kv)
    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        return self.call("kv_put", {"key": key, "value": value,
                                    "overwrite": overwrite})["existed"]

    def kv_get(self, key: str) -> Optional[bytes]:
        return self.call("kv_get", {"key": key})

    def kv_del(self, key: str) -> bool:
        return self.call("kv_del", {"key": key})["deleted"]

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self.call("kv_keys", {"prefix": prefix})

    def kv_exists(self, key: str) -> bool:
        return self.call("kv_exists", {"key": key})

    def close(self) -> None:
        self._closed = True
        self._conn.close()

    @property
    def closed(self) -> bool:
        return self._conn.closed


def main():  # pragma: no cover - spawned as a subprocess
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-dir", default=None)
    parser.add_argument("--address-file", default=None)
    args = parser.parse_args()
    from ray_tpu._private.logging_utils import (enable_stack_dumps,
                                                 setup_component_logging)
    setup_component_logging("gcs_server", args.session_dir)
    enable_stack_dumps(args.session_dir)
    persist = (os.path.join(args.session_dir, "gcs_snapshot.pkl")
               if args.session_dir else None)
    server = GcsServer(args.host, args.port, persist_path=persist)
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": server.address[0],
                       "port": server.address[1]}, f)
        os.replace(tmp, args.address_file)
    logger.info("GCS serving at %s", server.address)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
