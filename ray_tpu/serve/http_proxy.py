"""HTTP ingress: aiohttp proxy actor routing to deployment handles.

Analog of /root/reference/python/ray/serve/_private/http_proxy.py
(HTTPProxyActor :387, HTTPProxy :218, uvicorn/starlette there; aiohttp
here — starlette isn't baked in). Routes ``/{deployment}`` with a JSON
body to ``handle.remote(body)``.  The request path stays ON the event
loop (``DeploymentHandle.try_remote`` + owned-object readiness
callbacks); the blocking executor is a fallback for backpressured
submits and cross-node result pulls only (round-4 redesign — the old
executor-per-request path throttled the proxy at the thread pool).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


class HTTPProxyActor:
    """Threaded actor: aiohttp server runs on a background event loop."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def ready(self) -> bool:
        self._ready.wait(timeout=15)
        return self._ready.is_set()

    def _get_handle(self, deployment: str) -> DeploymentHandle:
        if deployment not in self._handles:
            self._handles[deployment] = DeploymentHandle(deployment)
        return self._handles[deployment]

    def _serve(self):
        import time as _time

        from aiohttp import web

        from ray_tpu.runtime.core_worker import get_global_worker
        from ray_tpu.serve.frontdoor import sse as fd_sse
        from ray_tpu.serve.handle import DisaggHandle, _aget
        from ray_tpu.util.tracing import tracing_helper as trh

        # per-request closures touch only locals: worker/handle lookups,
        # monotonic, and the json codec are bound once (the proxy's whole
        # budget on this box is fractions of a millisecond per request)
        worker = get_global_worker()
        get_handle = self._get_handle
        monotonic = _time.monotonic
        add_ready = worker.add_ready_callback
        ray_get = ray_tpu.get
        GetTimeout = ray_tpu.exceptions.GetTimeoutError
        ingress_root = trh.serve_ingress_root
        install_ctx = trh.install
        uninstall_ctx = trh.uninstall
        finish_request = trh.finish_request
        stream_sse = fd_sse.stream_sse
        # disagg routers are long-lived (they cache routing tables and
        # the prefix-affinity index); one per preset, bound outside the
        # handlers like the deployment handles
        disagg_handles: Dict[str, DisaggHandle] = {}

        def get_disagg(preset: str) -> DisaggHandle:
            h = disagg_handles.get(preset)
            if h is None:
                h = disagg_handles[preset] = DisaggHandle(
                    f"llm-{preset}-prefill", f"llm-{preset}-decode")
            return h

        async def handle(request: web.Request) -> web.Response:
            deployment = request.match_info["deployment"]
            if request.can_read_body:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query)
            loop = asyncio.get_running_loop()

            # request trace root (docs/observability.md): every HTTP
            # request gets a root context (SLO accounting classifies all
            # of them; span recording follows the deterministic
            # sampler).  Installed on THIS coroutine's context only —
            # concurrent requests interleave with their own identities.
            root = ingress_root(f"http:{deployment}", route=deployment)
            if root is not None:
                install_ctx(root.ctx())
            t_req = monotonic()

            # Fast path stays ON the event loop end to end: non-blocking
            # submit (try_remote), readiness via an owned-object ready
            # callback, and an immediate local get once ready.  Executor
            # hops happen only under backpressure (blocking admission)
            # or when a large result needs a cross-node pull — the two
            # cases that would otherwise stall every other request.
            try:
                deadline = monotonic() + 60.0
                h = get_handle(deployment)
                ref = h.try_remote(payload)
                if ref is None:        # cold table / backpressure
                    # bind_ctx: the executor thread must carry this
                    # request's context, or the handle would open a
                    # second root for the same request
                    ref = await loop.run_in_executor(
                        None, trh.bind_ctx(
                            root.ctx() if root is not None else None,
                            h.remote, payload))
                fut = loop.create_future()

                def _on_ready():
                    loop.call_soon_threadsafe(_set_ready, fut)

                add_ready(ref, _on_ready)
                # manual timeout (call_later + cancel) instead of
                # asyncio.wait_for: wait_for wraps the await in a Task —
                # measurable per-request overhead at these rates.  The
                # timer spends the REMAINING request budget (a blocked
                # executor submit already consumed part of the 60 s)
                timer = loop.call_later(
                    max(0.1, deadline - monotonic()), _fail_timeout, fut)
                try:
                    await fut
                finally:
                    timer.cancel()
                try:
                    # ready + inline/local result: returns without waiting
                    result = ray_get(ref, timeout=0.05)
                except GetTimeout:
                    # store-resident result needing a pull: off the loop
                    remaining = max(0.1, deadline - monotonic())
                    result = await loop.run_in_executor(
                        None, lambda: ray_get(ref, timeout=remaining))
            except Exception as e:  # noqa: BLE001 - surfaced as HTTP 500
                finish_request(root, pool="http", route=deployment,
                               status=trh.ERROR,
                               ttft_s=monotonic() - t_req,
                               error_type=type(e).__name__,
                               dossier_id=getattr(e, "dossier_id", None))
                return web.json_response(
                    {"error": type(e).__name__, "message": str(e)},
                    status=500)
            # non-streaming HTTP: the whole request latency IS its TTFT
            finish_request(root, pool="http", route=deployment,
                           ttft_s=monotonic() - t_req)
            try:
                return web.json_response(result)
            except TypeError:
                return web.Response(text=str(result))

        def _set_ready(fut):
            if not fut.done():
                fut.set_result(None)

        def _fail_timeout(fut):
            if not fut.done():
                fut.set_exception(TimeoutError("request timed out"))

        async def stream_colocated(request: web.Request):
            """SSE token streaming from a colocated LLM deployment
            (docs/serve_frontdoor.md): POST /-/stream/{deployment} with
            an LLM request body; the replica's ``stream`` method is
            driven via the streaming handle path and each yielded item
            is framed as an SSE event the moment its ref resolves."""
            deployment = request.match_info["deployment"]
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "BadRequest",
                     "message": "SSE streaming needs a JSON body"},
                    status=400)
            root = ingress_root(f"sse:{deployment}", route=deployment)
            token = install_ctx(root.ctx()) if root is not None else None
            try:
                loop = asyncio.get_running_loop()
                h = get_handle(deployment)
                try:
                    # routing may block (capacity wait, cold-table
                    # controller RPC): off the loop, ctx re-bound
                    gen = await loop.run_in_executor(
                        None, trh.bind_ctx(
                            root.ctx() if root is not None else None,
                            lambda: h.stream.remote_streaming(payload)))
                except Exception as e:  # noqa: BLE001 - HTTP 500 below
                    finish_request(root, pool="sse", route=deployment,
                                   status=trh.ERROR,
                                   error_type=type(e).__name__)
                    return web.json_response(
                        {"error": type(e).__name__, "message": str(e)},
                        status=500)

                async def items():
                    async for ref in gen:
                        yield await _aget(worker, ref, timeout=60.0)

                return await stream_sse(request, items(),
                                        route=deployment, pool="sse",
                                        root=root)
            finally:
                if token is not None:
                    uninstall_ctx(token)

        async def stream_disagg(request: web.Request):
            """SSE token streaming through the disaggregated router
            (docs/serve_frontdoor.md): POST /-/disagg/{preset} streams
            DisaggHandle.stream — first token from the prefill pool
            (prefix-affinity routed), decode tokens after the handoff,
            ``{"retry": n}`` death-recovery markers as SSE retry
            events.  The ingress root opened HERE is the request's
            trace root (DisaggHandle joins it instead of opening its
            own) so the SLO verdict carries client-observed latency."""
            preset = request.match_info["preset"]
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "BadRequest",
                     "message": "SSE streaming needs a JSON body"},
                    status=400)
            route = f"llm-{preset}-decode"
            root = ingress_root(f"sse:disagg:{preset}", route=route)
            token = install_ctx(root.ctx()) if root is not None else None
            try:
                dh = get_disagg(preset)
                return await stream_sse(request, dh.stream(payload),
                                        route=route, pool="disagg",
                                        root=root)
            finally:
                if token is not None:
                    uninstall_ctx(token)

        async def healthz(_request):
            return web.Response(text="ok")

        async def echo(request):
            """Transport+JSON floor probe: everything the proxy does per
            request EXCEPT the serve hop (benchmarks/serve_qps.py reads
            the serve_http row against this ceiling)."""
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                payload = None
            return web.json_response(payload)

        async def main():
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_post("/-/echo", echo)
            app.router.add_post("/-/stream/{deployment}",
                                stream_colocated)
            app.router.add_post("/-/disagg/{preset}", stream_disagg)
            app.router.add_route("*", "/{deployment}", handle)
            app.router.add_route("*", "/{deployment}/{tail:.*}", handle)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._ready.set()
            await asyncio.Event().wait()

        asyncio.run(main())
