"""HTTP ingress: aiohttp proxy actor routing to deployment handles.

Analog of /root/reference/python/ray/serve/_private/http_proxy.py
(HTTPProxyActor :387, HTTPProxy :218, uvicorn/starlette there; aiohttp
here — starlette isn't baked in). Routes ``/{deployment}`` with a JSON
body to ``handle.remote(body)``; replica calls run in an executor so the
event loop stays free.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


class HTTPProxyActor:
    """Threaded actor: aiohttp server runs on a background event loop."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def ready(self) -> bool:
        self._ready.wait(timeout=15)
        return self._ready.is_set()

    def _get_handle(self, deployment: str) -> DeploymentHandle:
        if deployment not in self._handles:
            self._handles[deployment] = DeploymentHandle(deployment)
        return self._handles[deployment]

    def _serve(self):
        from aiohttp import web

        async def handle(request: web.Request) -> web.Response:
            deployment = request.match_info["deployment"]
            if request.can_read_body:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query)
            loop = asyncio.get_running_loop()

            # Submission runs in the executor (it can momentarily block on
            # backpressure), but the thread is released immediately: the
            # reply is awaited via an owned-object ready callback, so no
            # thread is parked for the request's full duration (the
            # reference's fully-async proxy→replica path).
            def submit():
                return self._get_handle(deployment).remote(payload)

            try:
                import time as _time
                deadline = _time.monotonic() + 60.0
                ref = await loop.run_in_executor(None, submit)
                fut = loop.create_future()

                def _on_ready():
                    def _resolve():
                        if not fut.done():
                            fut.set_result(None)
                    loop.call_soon_threadsafe(_resolve)

                from ray_tpu.runtime.core_worker import get_global_worker
                get_global_worker().add_ready_callback(ref, _on_ready)
                # one 60 s budget end to end: readiness wait + the fetch
                # (a large result may still need a cross-node pull, which
                # must not run on the event loop)
                await asyncio.wait_for(
                    fut, timeout=max(0.1, deadline - _time.monotonic()))
                remaining = max(0.1, deadline - _time.monotonic())
                result = await loop.run_in_executor(
                    None, lambda: ray_tpu.get(ref, timeout=remaining))
            except Exception as e:  # noqa: BLE001 - surfaced as HTTP 500
                return web.json_response(
                    {"error": type(e).__name__, "message": str(e)},
                    status=500)
            try:
                return web.json_response(result)
            except TypeError:
                return web.Response(text=str(result))

        async def healthz(_request):
            return web.Response(text="ok")

        async def main():
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_route("*", "/{deployment}", handle)
            app.router.add_route("*", "/{deployment}/{tail:.*}", handle)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._ready.set()
            await asyncio.Event().wait()

        asyncio.run(main())
