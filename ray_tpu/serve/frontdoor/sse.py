"""Server-sent-events streaming bridge (docs/serve_frontdoor.md).

Turns the serve layer's token async-generators (``DisaggHandle.stream``,
a colocated replica's streaming path) into an HTTP ``text/event-stream``
response.  Framing rules:

- ``{"token": id}`` items ship as default (unnamed) SSE messages — the
  high-rate payload stays one ``data:`` line per token;
- ``{"retry": n, ...}`` mid-stream recovery markers (a replica died
  under the stream and the router re-drove it) ship as ``event: retry``
  so a client can surface "reconnecting" without parsing payloads;
- the final summary dict ships as ``event: done``;
- a server-side failure ships as ``event: error`` and ends the stream.

Backpressure is per-connection and free: ``StreamResponse.write`` is
awaited for every event, and aiohttp's flow control suspends the
coroutine when the socket's write buffer is over its high-water mark —
a slow client stalls only its own generator (token production for that
request), never the proxy loop or other connections.

The bridge also owns the ingress side of SLO accounting: it clocks
CLIENT-OBSERVED first-token and inter-token latency (what the serving
paper's SLOs are defined on, not engine-internal timestamps) and closes
the request's ingress trace root with the verdict — these roots are
what the controller's re-roling policy reads per route
(``trace_stats()["slo_by_route"]``).

No jax imports; aiohttp is imported lazily so ``frontdoor.prefix``
users never pay for it.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, AsyncIterator, Dict, Optional

from ray_tpu.util.tracing import tracing_helper as trh

SSE_HEADERS = {
    "Content-Type": "text/event-stream",
    "Cache-Control": "no-cache",
    # proxies (nginx) buffer unnamed content types; SSE must flush
    "X-Accel-Buffering": "no",
}


def format_event(data: Any, event: Optional[str] = None) -> bytes:
    """One SSE frame: optional ``event:`` name + one JSON ``data:``
    line.  Compact separators — the token path ships thousands of
    these per stream."""
    payload = json.dumps(data, separators=(",", ":"), default=str)
    head = f"event: {event}\n" if event else ""
    return f"{head}data: {payload}\n\n".encode()


def classify(item: Dict[str, Any]) -> Optional[str]:
    """SSE event name for one stream item (None = default message)."""
    if "token" in item:
        return None
    if "retry" in item:
        return "retry"
    return "done"


async def stream_sse(request, agen: AsyncIterator[Dict[str, Any]], *,
                     route: str, pool: str = "sse", root=None):
    """Bridge ``agen`` onto an SSE response for ``request``.

    ``root`` is the proxy's ingress trace root (or None when tracing is
    off): closed here with client-observed TTFT/TPOT and the outcome —
    OK on a drained stream, CANCELLED when the client hung up (socket
    reset / task cancellation; not a service failure, excluded from
    both SLO counters), ERROR when the generator raised."""
    from aiohttp import web

    resp = web.StreamResponse(headers=dict(SSE_HEADERS))
    await resp.prepare(request)
    t0 = time.perf_counter()
    first = last = None
    ntok = 0
    failure: Optional[BaseException] = None
    try:
        async for item in agen:
            ev = classify(item)
            if ev is None:
                now = time.perf_counter()
                if first is None:
                    first = now
                last = now
                ntok += 1
            await resp.write(format_event(item, ev))
        await resp.write_eof()
    except (ConnectionError, asyncio.CancelledError) as e:
        failure = e                      # client walked away mid-stream
    except Exception as e:  # noqa: BLE001 - surfaced as an SSE error event
        failure = e
        try:
            await resp.write(format_event(
                {"error": type(e).__name__, "message": str(e)}, "error"))
            await resp.write_eof()
        except (ConnectionError, asyncio.CancelledError):
            pass
    finally:
        aclose = getattr(agen, "aclose", None)
        if aclose is not None:
            try:
                await aclose()
            except Exception:
                pass
        if root is not None:
            if failure is None:
                status = trh.OK
            elif isinstance(failure, (ConnectionError,
                                      asyncio.CancelledError)):
                status = trh.CANCELLED
            else:
                status = trh.ERROR
            tpot_s = None
            if ntok > 1 and first is not None:
                tpot_s = (last - first) / (ntok - 1)
            trh.finish_request(
                root, pool=pool, route=route, status=status,
                ttft_s=(first - t0) if first is not None else None,
                tpot_s=tpot_s, num_tokens=ntok,
                error_type=(type(failure).__name__
                            if failure is not None else None),
                dossier_id=getattr(failure, "dossier_id", None))
        if isinstance(failure, asyncio.CancelledError):
            raise failure
    return resp
