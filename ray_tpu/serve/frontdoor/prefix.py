"""Prompt-prefix digests and the router-side prefix-affinity index.

Two cooperating halves share the digest contract defined here
(docs/serve_frontdoor.md):

- the paged LLM engine (serve/llm_engine.py) retains full prompt pages
  after prefill keyed by the CHAINED per-page digest of the tokens they
  hold, and advertises the resident boundary digests on the controller
  load-publish path;
- routers (serve/handle.py DisaggHandle, and through it the HTTP front
  door) compute the same chain over an incoming prompt, walk it
  deepest-first against the advertised index, and pin the prefill hop
  to a replica that can skip re-prefilling the shared prefix.

The chain is ``d_0 = H(tok[0:ps])``, ``d_i = H(d_{i-1} || tok[i*ps :
(i+1)*ps])`` over FULL pages only — a boundary digest therefore names
the page-aligned token prefix exactly, and matching ``d_i`` anywhere
implies the whole prefix up to page ``i`` matches.  No jax imports:
this module runs in proxies and driver handles.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set

from ray_tpu._private import runtime_metrics as rtm

_M_PREFIX_HIT = rtm.counter_family(
    "ray_tpu_serve_prefix_hit",
    "Router prefix-affinity lookups by outcome: hit (pinned to an "
    "advertising replica), miss (no advertised prefix), evicted (the "
    "index knew the digest but no advertising replica remains).",
    tag_keys=("outcome",))

_DIGEST_BYTES = 16


def page_digests(tokens: Sequence[int], page_size: int) -> List[str]:
    """Chained per-page digest boundaries of ``tokens`` (hex), full
    pages only.  ``page_digests(t, ps)[i]`` names ``t[:(i+1)*ps]``."""
    if page_size <= 0:
        return []
    out: List[str] = []
    prev = b""
    for i in range(len(tokens) // page_size):
        m = hashlib.blake2b(prev, digest_size=_DIGEST_BYTES)
        for t in tokens[i * page_size:(i + 1) * page_size]:
            m.update(int(t).to_bytes(8, "little", signed=True))
        prev = m.digest()
        out.append(prev.hex())
    return out


def record_outcome(outcome: str) -> None:
    """Count a lookup outcome on ray_tpu_serve_prefix_hit{outcome}."""
    _M_PREFIX_HIT.inc(outcome)


class PrefixIndex:
    """Bounded digest -> replica-set map fed from published targets.

    ``update(deployment_prefixes)`` replaces each replica's advertised
    digest set (the controller publishes the full current set every
    reply, like loads); ``lookup(chain, live)`` walks a prompt's chain
    deepest-first and returns the advertising replica still in the
    live routing set.  LRU-bounded at ``max_entries`` digests — the
    advertisement path is already bounded per replica, this caps the
    union across a large pool."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        # digest -> {replica_tag, ...}; OrderedDict for LRU rotation
        self._index: "OrderedDict[str, Set[str]]" = OrderedDict()
        self._by_replica: Dict[str, Set[str]] = {}

    def update(self, replica: str, digests: Sequence[str]) -> None:
        new = set(digests or ())
        with self._lock:
            old = self._by_replica.get(replica, set())
            for d in old - new:
                holders = self._index.get(d)
                if holders is not None:
                    holders.discard(replica)
                    if not holders:
                        self._index.pop(d, None)
            for d in new - old:
                holders = self._index.get(d)
                if holders is None:
                    holders = self._index[d] = set()
                holders.add(replica)
            if new:
                self._by_replica[replica] = new
            else:
                self._by_replica.pop(replica, None)
            while len(self._index) > self.max_entries:
                d, holders = self._index.popitem(last=False)
                for r in holders:
                    owned = self._by_replica.get(r)
                    if owned is not None:
                        owned.discard(d)

    def drop_replica(self, replica: str) -> None:
        self.update(replica, ())

    def lookup(self, chain: Sequence[str],
               live: Optional[Set[str]] = None) -> Optional[str]:
        """Deepest advertising replica for ``chain``, restricted to
        ``live`` replica tags when given.  Counts the outcome on the
        ray_tpu_serve_prefix_hit metric family: ``evicted`` means the
        digest was known but every advertising replica has left the
        routing set — the affinity decayed under churn, not a miss."""
        known_dead = False
        with self._lock:
            for d in reversed(chain or ()):
                holders = self._index.get(d)
                if not holders:
                    continue
                pick = None
                for r in holders:
                    if live is None or r in live:
                        pick = r
                        break
                if pick is not None:
                    self._index.move_to_end(d)
                    record_outcome("hit")
                    return pick
                known_dead = True
        record_outcome("evicted" if known_dead else "miss")
        return None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"digests": len(self._index),
                    "replicas": len(self._by_replica)}
