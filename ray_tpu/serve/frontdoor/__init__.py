"""HTTP-native serving front door (docs/serve_frontdoor.md).

Three planes, each importable on its own so lightweight processes pull
only what they use:

- ``prefix``: prompt-prefix digest chain + the router-side affinity
  index (no jax, no aiohttp — runs in proxies, handles and the engine).
- ``sse``: server-sent-events framing and the async bridge from
  ``DisaggHandle.stream`` to an HTTP response (no jax).
- re-roling lives in the serve controller (serve/controller.py); the
  episode plane is metrics_history.RecoveryAuditor kind ``rerole``.

Submodules are lazy: ``frontdoor.sse`` pulls tracing helpers the
engine-side ``prefix`` import must not pay for.
"""

from __future__ import annotations

_SUBMODULES = ("prefix", "sse")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib
        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
