"""Continuous-batching LLM inference engine (the TPU serving core).

The reference serves LLMs by scaling replicas and batching whole requests
(`python/ray/serve/batching.py`); its Serve LLM benchmark surface is
llama-3-8b qps/p50/p99 (BASELINE.md north-star row).  On TPU the win is
*iteration-level* scheduling (Orca-style): one jitted decode step over a
fixed slot grid, with requests admitted into free KV-cache slots and
evicted the step they finish — no compile-shape churn, no head-of-line
blocking behind a long generation.

Design (shaped by one hard constraint: on a remote-chip transport every
device->host fetch costs a full round-trip that outweighs a decode step
~12x, so the engine does exactly ONE fetch per scheduling quantum):

  - The KV cache is one global [num_slots+1, max_seq, ...] buffer per
    layer (gpt.py ``_decode_attend`` slot mode: per-row write positions
    + a position mask, so every row sits at a different offset).  Row
    ``num_slots`` is a scratch slot that absorbs padded admission
    writes; it is never scheduled.
  - Prefill runs per admission WAVE: prompts sharing a power-of-two
    length bucket run as one batched forward (one compile per
    (bucket, wave-size) pair), each first token is sampled inside the
    same jit, and the prompt K/V blocks are scattered into their slots
    in one call.  Right-pad garbage beyond a real prompt length is
    always overwritten by a decode write before the position mask makes
    it visible, so padding needs no extra masking.
  - One jitted ``block step`` advances ALL slots ``block_size`` tokens
    via lax.scan: [N] tokens in, [N, K] tokens out, donated cache.
    Newly admitted slots get their first token scattered in on-device
    (the host never sees it before dispatch), and the block output and
    the admission first-tokens come back in a single combined fetch.
  - No eos logic on device: rows that finish mid-block keep generating
    junk the host truncates; a freed slot keeps stepping junk until
    it is reused (the grid is fixed — those steps are free).
  - Per-request temperature rides as an [N] array (greedy rows select
    argmax under the same jit); top_k/top_p are engine-static.

The host loop owns admission/eviction and runs on a plain thread;
``submit`` is loop-aware like serve's ``_BatchQueue.submit`` (awaitable
from an async replica, blocking from a plain thread).

PAGED MODE (``paged=True``) replaces the dense per-slot ``[max_seq]``
cache rows with a shared page pool + per-row block tables
(ops/paged_attention.py):

  - HBM: decode attention reads only the pages a row occupies (the
    Pallas kernel's fori_loop bound is the row's page count), so long
    ``max_seq_len`` stops costing bandwidth per step, and KV capacity
    is pooled instead of reserved per slot.
  - TTFT: prefill becomes SLOTLESS — a queued request's prompt K/V is
    written straight into freshly allocated pages and its first token
    sampled *before* any decode slot frees (prefill-ahead).  Requests
    then wait in a ready queue holding their first token; a freeing
    slot "installs" one by uploading its (token, position, table) row
    into the block step's device state.  Time-to-first-token is bounded
    by prefill throughput and pool capacity, not by slot turnover —
    the saturation-TTFT fix the dense engine could not express.
  - Safety: a freed slot keeps stepping junk until its redirect row
    (table -> scratch page 0) rides the next block dispatch; pages are
    recycled only through dispatches ordered after the last junk write
    (device stream order), so reuse can never corrupt a live request.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import threading
import time
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.models.gpt import GPT
from ray_tpu.serve.frontdoor.prefix import page_digests

# admission waves are padded to the next of these sizes (bounded jit
# specializations per prompt bucket); the top size bounds how many
# prompts one prefill dispatch carries — on a remote-chip transport the
# per-dispatch round-trip dwarfs the prefill compute, so saturation
# bursts (prefill-ahead admitting a whole queue) want wide waves
_WAVE_SIZES = (1, 2, 4, 8, 16, 32)


@dataclasses.dataclass
class GenerationResult:
    tokens: List[int]
    finish_reason: str                    # "eos" | "length"
    prompt_len: int
    time_to_first_token_s: float
    latency_s: float


# re-exported here for engine-local users; defined in ray_tpu.exceptions
# so client-side routers can catch it without importing the jax-heavy
# engine module.  Raised synchronously by import_prefill when the
# import wait queue hits its cap (import_queue_max) — see that method's
# docstring for the FIFO-wait-vs-reject contract.
from ray_tpu.exceptions import KVPoolFullError  # noqa: E402


@dataclasses.dataclass
class PrefillHandoff:
    """A prefilled request packaged for decode on ANOTHER engine.

    ``kv`` is the request's occupied pool pages gathered into ONE
    contiguous host array ``[n_pool_leaves, npages, kv_heads,
    page_size, 2*head_dim]`` (K/V fused exactly as the pool stores
    them, ops/paged_attention.py layout) — one ``jax.device_get``
    round-trip on export, one ``device_put`` + page-table remap on
    import.  Only ``ceil(prompt_len / page_size)`` pages ship: the
    first generated token's K/V is written by the importer's first
    decode step (same invariant as a locally-prefilled install).
    ``kv is None`` when the request finished at its first token
    (``finish_reason`` set) — nothing to decode."""

    kv: Optional[Any]                     # np.ndarray, layout above
    page_size: int
    npages: int
    prompt_len: int
    first_token: int
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    finish_reason: Optional[str] = None   # set: done at first token
    export_ms: float = 0.0                # prefill->gather->fetch wall
    # wire-codec fields (docs/serve_frontdoor.md, serve_handoff_quantize):
    # when ``codec`` is set, ``kv`` holds the ENCODED uint8 wire buffer
    # and shape/dtype/raw_nbytes describe the original array — the serve
    # layer (llm.py) encodes after export and decodes before import, so
    # the engine only ever sees the raw layout.
    codec: Optional[str] = None
    kv_shape: Optional[tuple] = None
    kv_dtype: Optional[str] = None
    raw_nbytes: int = 0

    @property
    def nbytes(self) -> int:
        return int(self.kv.nbytes) if self.kv is not None else 0


@dataclasses.dataclass
class _Request:
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    deliver: Callable[[bool, Any], None]
    on_token: Optional[Callable[[int], None]]
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    delivered: bool = False
    export: bool = False                  # deliver a PrefillHandoff
    # chained page-boundary digests of the prompt (frontdoor/prefix.py),
    # computed at submit when the prefix cache is enabled
    digests: Optional[List[str]] = None


@dataclasses.dataclass
class _Import:
    """A PrefillHandoff waiting for pool pages on the decode engine.
    ``need`` is the page count for the FULL generation span (prompt +
    new tokens, capped by the importing engine's max_seq_len) — decode
    writes continue past the shipped prompt pages."""
    handoff: PrefillHandoff
    request: _Request
    need: int


class _Slot:
    __slots__ = ("request", "pos", "out", "last_token", "first_token_at",
                 "pages", "prompt_len", "borrowed", "prefix_entry")

    def __init__(self, request: _Request, prompt_len: int, first_token: int,
                 pages: Optional[List[int]] = None,
                 borrowed: int = 0, prefix_entry=None):
        self.request = request
        self.pos = prompt_len            # next write position
        self.out = [first_token]
        self.last_token = first_token
        self.first_token_at = time.monotonic()
        self.pages = pages or []         # paged mode: physical pages owned
        self.prompt_len = prompt_len
        # prefix-cache hit bookkeeping: the first ``borrowed`` entries of
        # ``pages`` are SHARED read-only prefix pages owned by
        # ``prefix_entry`` — never freed here, refcount released instead
        self.borrowed = borrowed
        self.prefix_entry = prefix_entry


class _PrefixEntry:
    """A retained run of full prompt pages, shared read-only across
    hits.  ``chain[i]`` digests the tokens ``pages[:i+1]`` hold."""

    __slots__ = ("pages", "chain", "refs", "last_used")

    def __init__(self, pages: List[int], chain: List[str]):
        self.pages = pages
        self.chain = chain
        self.refs = 0
        self.last_used = 0


class _Prefilled:
    """Paged mode: a request whose prompt K/V already sits in pool pages
    and whose first token is known, waiting for a decode slot."""

    __slots__ = ("slot_state", "table")

    def __init__(self, slot_state: _Slot, table):
        self.slot_state = slot_state     # reused verbatim at install
        self.table = table               # np.int32 [max_pages]


class EngineStats:
    """Occupancy / throughput counters, read by benchmarks and /stats."""

    def __init__(self):
        self.steps = 0                   # decode steps executed (N-wide)
        self.step_tokens = 0             # tokens delivered from steps
        self.tokens_generated = 0        # + prefill first tokens
        self.prefills = 0
        self.requests_completed = 0
        self.exports = 0                 # prefill handoffs shipped out
        self.imports = 0                 # prefill handoffs admitted
        self.import_rejects = 0          # pool-full import rejections
        self.prefix_hits = 0             # prefills served from cached pages
        self.prefix_misses = 0           # cache enabled but no usable match
        self.prefix_tokens_saved = 0     # prompt tokens NOT re-prefilled
        self.prefix_evictions = 0        # retained runs evicted (LRU/space)

    def occupancy(self, num_slots: int) -> float:
        """Fraction of step-slots that produced a delivered token (junk
        decoded past eos / on freed slots counts against it)."""
        return (self.step_tokens / (self.steps * num_slots)
                if self.steps else 0.0)

    def snapshot(self, num_slots: int) -> dict:
        return {
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "requests_completed": self.requests_completed,
            "batch_occupancy": round(self.occupancy(num_slots), 4),
            "exports": self.exports,
            "imports": self.imports,
            "import_rejects": self.import_rejects,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "prefix_evictions": self.prefix_evictions,
        }


class LLMEngine:
    """Slot-scheduled KV-cache decoder around a GPT-family checkpoint."""

    def __init__(self, cfg: TransformerConfig, params, *,
                 num_slots: int = 8, max_prompt_len: Optional[int] = None,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 min_prefill_bucket: int = 16, block_size: int = 32,
                 max_seq_len: Optional[int] = None,
                 paged: bool = False, page_size: int = 64,
                 kv_pool_pages: Optional[int] = None,
                 import_queue_max: Optional[int] = None,
                 prefix_cache_pages: int = 0):
        # Inference engine owns its own copies of the knobs a server
        # tunes independently of training:
        #  - max_seq_len: the KV allocation AND the per-step attention
        #    read span.  Decode attends over the whole cache row every
        #    step, so serving 128-token chats with a 8192-long cache
        #    reads 64x more HBM than needed — size it to the workload.
        #  - dtype: params are cast to the activation dtype once here;
        #    serving never needs f32 master weights, and keeping them
        #    would re-cast (and re-read) the full parameter set every
        #    decode step.
        if max_seq_len is not None:
            cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)
        self.cfg = cfg
        self.params = jax.tree.map(
            lambda p: p.astype(cfg.dtype) if hasattr(p, "astype") else p,
            params)
        self.num_slots = num_slots
        self.top_k = top_k
        self.top_p = top_p
        self.max_prompt_len = max_prompt_len or cfg.max_seq_len // 2
        self._min_bucket = min_prefill_bucket
        self.block_size = block_size
        self.paged = paged
        if paged:
            self.page_size = page_size
            self.max_pages = -(-cfg.max_seq_len // page_size)
            # page 0 is the scratch page (zeroed tables point there).
            # Default pool: HBM PARITY with the dense cache — the dense
            # engine allocates (num_slots + 1) full-length rows (the +1
            # is the scratch row), i.e. (num_slots + 1) * max_pages
            # page-equivalents, so flipping paged=True on a deployment
            # that fit in dense mode can never OOM it.  The old default
            # (4 * num_slots * max_pages) allocated ~4x the dense
            # cache's HBM for prefill-ahead headroom; deployments that
            # want the ready queue to prefill well ahead of slot
            # turnover should pass kv_pool_pages explicitly (e.g.
            # benchmarks/serve_llm.py sizes it per request load).
            self.kv_pool_pages = (kv_pool_pages if kv_pool_pages
                                  else 1 + (num_slots + 1) * self.max_pages)
            self.model = GPT(cfg, decode=True,
                             paged_pages=self.kv_pool_pages,
                             page_size=page_size)
        else:
            self.model = GPT(cfg, decode=True)
        self.stats = EngineStats()

        self._rng = jax.random.PRNGKey(seed)
        self._lock = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._slots: List[Optional[_Slot]] = [None] * num_slots
        self._free: List[int] = list(range(num_slots))[::-1]
        self._closed = False
        self._thread: Optional[threading.Thread] = None

        # +1 scratch row absorbing padded admission writes
        self._rows = num_slots + 1
        self._cache = self._init_cache(self._rows)
        # decode state lives ON DEVICE between quanta (tokens, positions,
        # temps, rng): the host uploads only the small admit arrays, and
        # only when something was admitted
        self._state = self._init_state(seed)
        # packed admit metadata [3, num_slots]: slots row, positions row,
        # temps*1e6 row — one upload per quantum, cached when empty
        no_meta = np.zeros((3, num_slots), np.int32)
        no_meta[0, :] = num_slots                           # -> scratch
        self._prefill_jit: dict = {}      # (bucket, wave) -> jitted fn
        self._insert_jit: dict = {}       # (bucket, wave) -> jitted fn
        if paged:
            self._no_admit = (jnp.asarray(no_meta),
                              jnp.zeros((num_slots,), jnp.int32),
                              jnp.zeros((num_slots, self.max_pages),
                                        jnp.int32))
            self._free_pages: List[int] = list(
                range(1, self.kv_pool_pages))[::-1]
            self._ready: collections.deque = collections.deque()
            self._stale_slots: set = set()   # evicted, redirect pending
            self._imports: collections.deque = collections.deque()
            # admitted-handoff wait-queue bound: beyond it
            # import_prefill rejects SYNCHRONOUSLY (KVPoolFullError) so
            # the caller can route elsewhere.  None (default) queues
            # without bound — a queued import costs one deque entry
            # plus its handoff bytes, and FIFO page allocation cannot
            # wedge (pages free as resident streams complete, exactly
            # the pending-prefill contract).  Routers that would
            # otherwise poll a full pool are the reason rejection is
            # a cap, not the default: at saturation, thousands of
            # re-queue round-trips/s cost more decode throughput than
            # the waiting ever could.
            self.import_queue_max = import_queue_max
            self._export_jit: dict = {}      # (page bucket, wave) -> fn
            self._import_jit: dict = {}      # (page bucket, wave) -> fn
            # optional observer called with the host-side remap wall
            # (ms) per admitted import wave — the serving layer feeds
            # its handoff-latency histogram without the engine growing
            # a telemetry dependency
            self.on_import_admit: Optional[Callable[[float], None]] = None
            # KV pool leaf identity + handoff shape: pool leaves carry
            # trailing [pool_pages, kv_heads, page_size, 2*head_dim]
            # (ops/paged_attention.py layout); _ltot counts total
            # per-layer pools across the cache tree (the scan axis of a
            # scanned leaf contributes its length) — the leading axis of
            # PrefillHandoff.kv, which both handoff ends must agree on.
            self._pool_tail = (cfg.n_kv_heads, page_size,
                               2 * cfg.head_dim)
            self._ltot = sum(
                (leaf.shape[0] if leaf.ndim == 5 else 1)
                for leaf in jax.tree.leaves(self._cache)
                if self._is_pool_leaf(leaf))
            self._block_jit = jax.jit(self._block_fn_paged,
                                      donate_argnums=(1, 2))
            # prompt-prefix page cache (docs/serve_frontdoor.md):
            # retained full prompt pages stay OUT of _free_pages, keyed
            # by their chained token digests; hits borrow them read-only
            # and prefill only the suffix.  The budget never exceeds the
            # pool minus one working page.
            self.prefix_cache_pages = max(
                0, min(int(prefix_cache_pages), self.kv_pool_pages - 2))
            self._prefix_lock = threading.Lock()
            self._prefix_index: dict = {}    # digest -> (_PrefixEntry, n)
            # deepest-digest -> entry, insertion-ordered for LRU
            self._prefix_entries: collections.OrderedDict = \
                collections.OrderedDict()
            self._prefix_pages_used = 0
            self._prefix_seq = 0
            if self.prefix_cache_pages:
                # same params/cache structure, different (static)
                # attention path: T>1 windows at nonzero offsets attend
                # back through the pool over borrowed prefix pages
                self.model_prefix = GPT(cfg, decode=True,
                                        paged_pages=self.kv_pool_pages,
                                        page_size=page_size,
                                        prefix_attend=True)
            self._suffix_jit: dict = {}      # (bucket, wave) -> jitted fn
        else:
            self.prefix_cache_pages = 0
            self._no_admit = (jnp.asarray(no_meta),
                              jnp.zeros((num_slots,), jnp.int32))
            self._block_jit = jax.jit(self._block_fn,
                                      donate_argnums=(1, 2))

    # ------------------------------------------------------------ jit fns

    def _init_cache(self, batch):
        from ray_tpu.models.generate import init_decode_cache
        return init_decode_cache(self.model, batch)

    def _init_state(self, seed: int):
        state = (jnp.zeros((self._rows,), jnp.int32),     # tokens
                 jnp.zeros((self._rows,), jnp.int32),     # positions
                 jnp.zeros((self._rows,), jnp.float32),   # temps
                 jax.random.PRNGKey(seed))                # device rng
        if self.paged:
            # + per-row block tables (zeros -> every page is scratch)
            state = state[:3] + (jnp.zeros(
                (self._rows, self.max_pages), jnp.int32),) + state[3:]
        return state

    def _sample_fn(self, rng, logits, temps):
        """[B, V] logits + per-row temperature -> [B] token ids
        (models/generate.py sample_logits, array-temperature form)."""
        from ray_tpu.models.generate import sample_logits
        return sample_logits(rng, logits, temperature=temps,
                             top_k=self.top_k, top_p=self.top_p)

    def _get_prefill(self, bucket: int, wave: int):
        fn = self._prefill_jit.get((bucket, wave))
        if fn is None:
            def prefill(params, packed, rng):
                # packed [wave, bucket+3]: right-padded prompt tokens,
                # then s_real, slot, temp*1e6 (single upload).  Per-row
                # last REAL logit selected by s_real; first tokens
                # sampled here so admission needs no host round-trip.
                tokens = packed[:, :bucket]
                s_reals = packed[:, bucket]
                slots = packed[:, bucket + 1]
                temps = packed[:, bucket + 2].astype(jnp.float32) / 1e6
                b, s = tokens.shape
                positions = jnp.broadcast_to(jnp.arange(s), (b, s))
                cache = self._init_cache(b)
                logits, mut = self.model.apply(
                    {"params": params, "cache": cache}, tokens, positions,
                    mutable=["cache"])
                last = jnp.take_along_axis(
                    logits, (s_reals - 1)[:, None, None], axis=1)[:, 0]
                first = self._sample_fn(rng, last, temps)
                return first, mut["cache"], slots
            fn = self._prefill_jit[(bucket, wave)] = jax.jit(prefill)
        return fn

    def _get_insert(self, bucket: int, wave: int):
        fn = self._insert_jit.get((bucket, wave))
        if fn is None:
            def insert(cache, pre, slots):
                # scatter each prefilled row's first `bucket` positions
                # into its slot; padded rows carry slot == num_slots
                # (the scratch row)
                def leaf(g, p):
                    # K/V leaves are [..., batch, seq, kv_heads, head_dim]
                    # (a leading layer axis under scan_layers): the batch
                    # axis sits at ndim-4 BY LAYOUT, never inferred from
                    # shapes — wave can equal the global row count.
                    # Lower-rank leaves (per-layer scalar "index") are
                    # engine-unused in slot mode: skip.
                    if g.ndim < 4:
                        return g
                    ax = g.ndim - 4
                    for r in range(wave):
                        row = jax.lax.slice_in_dim(p, r, r + 1, axis=ax)
                        row = jax.lax.slice_in_dim(row, 0, bucket,
                                                   axis=ax + 1)
                        start = [jnp.int32(0)] * g.ndim
                        start[ax] = slots[r]
                        g = jax.lax.dynamic_update_slice(g, row, start)
                    return g
                return jax.tree.map(leaf, cache, pre)
            fn = self._insert_jit[(bucket, wave)] = jax.jit(
                insert, donate_argnums=(0,))
        return fn

    def _block_fn(self, params, cache, state, admit_meta, a_firsts):
        """lax.scan of block_size decode steps: one dispatch, ONE
        combined [rows*K + num_slots] fetch of (token block, admission
        first tokens), and all decode state chained on device.  Newly
        admitted rows' tokens/positions/temps are scattered in here;
        admit_meta is one packed [3, num_slots] i32 upload (slots,
        positions, temps*1e6), padded so every quantum reuses one
        compiled program (pad slots point at the scratch row)."""
        tokens, positions, temps, rng = state
        a_slots = admit_meta[0]
        tokens = tokens.at[a_slots].set(a_firsts)
        positions = positions.at[a_slots].set(admit_meta[1])
        temps = temps.at[a_slots].set(
            admit_meta[2].astype(jnp.float32) / 1e6)
        rng, sub = jax.random.split(rng)
        keys = jax.random.split(sub, self.block_size)

        def one(carry, key):
            tokens, positions, cache = carry
            logits, mut = self.model.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                positions[:, None], mutable=["cache"])
            nxt = self._sample_fn(key, logits[:, -1], temps)
            positions = jnp.minimum(positions + 1,
                                    self.cfg.max_seq_len - 1)
            return (nxt, positions, mut["cache"]), nxt

        (tokens, positions, cache), block = jax.lax.scan(
            one, (tokens, positions, cache), keys)
        combined = jnp.concatenate([block.T.reshape(-1), a_firsts])
        return combined, (tokens, positions, temps, rng), cache

    # ------------------------------------------------ paged-mode jit fns

    def _get_prefill_paged(self, bucket: int, wave: int):
        """Slotless prefill: prompts write straight into pool pages via
        the model's paged path (the T>1 case of _decode_attend_paged);
        the per-row last REAL logit samples the first token in-jit.
        Donates the pool cache (it chains through every engine call)."""
        fn = self._prefill_jit.get((bucket, wave))
        if fn is None:
            def prefill(params, cache, packed, tables, rng):
                # packed [wave, bucket+2]: prompt tokens | s_real | temp*1e6
                tokens = packed[:, :bucket]
                s_reals = packed[:, bucket]
                temps = packed[:, bucket + 1].astype(jnp.float32) / 1e6
                b, s = tokens.shape
                positions = jnp.broadcast_to(jnp.arange(s), (b, s))
                logits, mut = self.model.apply(
                    {"params": params, "cache": cache}, tokens, positions,
                    block_tables=tables, mutable=["cache"])
                last = jnp.take_along_axis(
                    logits, (s_reals - 1)[:, None, None], axis=1)[:, 0]
                first = self._sample_fn(rng, last, temps)
                return first, mut["cache"]
            fn = self._prefill_jit[(bucket, wave)] = jax.jit(
                prefill, donate_argnums=(1,))
        return fn

    def _get_prefill_suffix(self, bucket: int, wave: int):
        """Prefix-cache hit prefill: like _get_prefill_paged but each
        row's window starts at a per-row offset (the cached page-aligned
        prefix length) and attends back through the pool — leading block
        table entries are BORROWED read-only prefix pages, the scatter
        touches only the fresh suffix pages past them (positions//ps >=
        the borrow count, offsets are page-aligned by construction)."""
        fn = self._suffix_jit.get((bucket, wave))
        if fn is None:
            def prefill(params, cache, packed, tables, offs, rng):
                # packed [wave, bucket+2]: suffix tokens|s_real|temp*1e6
                tokens = packed[:, :bucket]
                s_reals = packed[:, bucket]
                temps = packed[:, bucket + 1].astype(jnp.float32) / 1e6
                b, s = tokens.shape
                positions = offs[:, None] + jnp.broadcast_to(
                    jnp.arange(s), (b, s))
                logits, mut = self.model_prefix.apply(
                    {"params": params, "cache": cache}, tokens, positions,
                    block_tables=tables, mutable=["cache"])
                last = jnp.take_along_axis(
                    logits, (s_reals - 1)[:, None, None], axis=1)[:, 0]
                first = self._sample_fn(rng, last, temps)
                return first, mut["cache"]
            fn = self._suffix_jit[(bucket, wave)] = jax.jit(
                prefill, donate_argnums=(1,))
        return fn

    def _is_pool_leaf(self, leaf) -> bool:
        """A cache leaf holding the shared KV page pool: trailing
        [pool_pages, kv_heads, page_size, 2*head_dim] with optionally a
        leading scan-layer axis.  Other cache leaves (per-layer scalar
        indices) are handoff-irrelevant."""
        return (leaf.ndim in (4, 5)
                and leaf.shape[-4] == self.kv_pool_pages
                and tuple(leaf.shape[-3:]) == self._pool_tail)

    def _page_bucket(self, n: int) -> int:
        """Power-of-two page-count bucket: bounds the gather/scatter jit
        specializations the same way _bucket bounds prefill shapes."""
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_pages)

    def _get_export(self, bucket: int, wave: int):
        """Gather jit: pull ``wave`` requests' pool pages (``bucket``
        page slots each, pad slots point at scratch page 0) into ONE
        contiguous [wave, ltot, bucket, kvh, ps, 2hd] device array —
        fetched with a single device_get per export group.  Read-only
        on the cache (no donation): dispatched after this iteration's
        block step, so it reads the chained cache value in stream
        order, before any later dispatch can recycle the pages."""
        fn = self._export_jit.get((bucket, wave))
        if fn is None:
            def gather(cache, idx):
                flat = idx.reshape(-1)                  # [wave*bucket]
                parts = []
                for leaf in jax.tree.leaves(cache):
                    if not self._is_pool_leaf(leaf):
                        continue
                    ax = leaf.ndim - 4
                    g = jnp.take(leaf, flat, axis=ax)
                    if leaf.ndim == 5:
                        lc = leaf.shape[0]
                        g = g.reshape((lc, wave, bucket)
                                      + tuple(leaf.shape[-3:]))
                        g = jnp.moveaxis(g, 1, 0)
                    else:
                        g = g.reshape((wave, 1, bucket)
                                      + tuple(leaf.shape[-3:]))
                    parts.append(g)
                return jnp.concatenate(parts, axis=1)
            fn = self._export_jit[(bucket, wave)] = jax.jit(gather)
        return fn

    def _get_import(self, bucket: int, wave: int):
        """Scatter jit: the inverse remap — land a handoff's pages at
        freshly allocated LOCAL physical pages (pad rows/slots target
        scratch page 0, which absorbs garbage by contract).  Donates
        the cache like every other engine cache transform."""
        fn = self._import_jit.get((bucket, wave))
        if fn is None:
            def scatter(cache, kv, idx):
                # kv [wave, ltot, bucket, kvh, ps, 2hd]; idx [wave, bucket]
                flat = idx.reshape(-1)
                leaves, treedef = jax.tree_util.tree_flatten(cache)
                out = []
                off = 0                     # ltot cursor (trace-static)
                for leaf in leaves:
                    if not self._is_pool_leaf(leaf):
                        out.append(leaf)
                        continue
                    tail = tuple(leaf.shape[-3:])
                    if leaf.ndim == 5:
                        lc = leaf.shape[0]
                        src = jnp.moveaxis(kv[:, off:off + lc], 1, 0)
                        src = src.reshape((lc, wave * bucket) + tail)
                        out.append(leaf.at[:, flat].set(
                            src.astype(leaf.dtype)))
                        off += lc
                    else:
                        src = kv[:, off].reshape((wave * bucket,) + tail)
                        out.append(leaf.at[flat].set(
                            src.astype(leaf.dtype)))
                        off += 1
                return jax.tree_util.tree_unflatten(treedef, out)
            fn = self._import_jit[(bucket, wave)] = jax.jit(
                scatter, donate_argnums=(0,))
        return fn

    def _block_fn_paged(self, params, cache, state, admit_meta,
                        admit_lasts, admit_tables):
        """Paged block step.  Differences from _block_fn: per-row block
        tables ride the device state; installs upload their CURRENT last
        token (known to the host since the request's prefill quantum) so
        nothing extra is fetched; redirect rows (evicted slots) are just
        installs of (token 0, position 0, zero table -> scratch page)."""
        tokens, positions, temps, tables, rng = state
        a_slots = admit_meta[0]
        tokens = tokens.at[a_slots].set(admit_lasts)
        positions = positions.at[a_slots].set(admit_meta[1])
        temps = temps.at[a_slots].set(
            admit_meta[2].astype(jnp.float32) / 1e6)
        tables = tables.at[a_slots].set(admit_tables)
        rng, sub = jax.random.split(rng)
        keys = jax.random.split(sub, self.block_size)

        def one(carry, key):
            tokens, positions, cache = carry
            logits, mut = self.model.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                positions[:, None], block_tables=tables,
                mutable=["cache"])
            nxt = self._sample_fn(key, logits[:, -1], temps)
            positions = jnp.minimum(positions + 1,
                                    self.cfg.max_seq_len - 1)
            return (nxt, positions, mut["cache"]), nxt

        (tokens, positions, cache), block = jax.lax.scan(
            one, (tokens, positions, cache), keys)
        return (block.T.reshape(-1),
                (tokens, positions, temps, tables, rng), cache)

    # ------------------------------------------------------------- public

    def warmup(self, prompt_lens=(64,), burst: int = 0) -> None:
        """Compile every jit specialization the given prompt lengths can
        hit (all admission wave sizes per bucket + the block program) so
        no request pays compile latency.  Serve replicas call this at
        init; benchmarks call it before timing.

        ``burst`` (paged mode): additionally push that many 1-token
        dummy requests through the live loop at once, compiling the
        saturation-burst paths the per-function loops can't reach (the
        combined multi-wave fetch concat; its shape depends on the burst
        decomposition)."""
        buckets = sorted({self._bucket(n) for n in prompt_lens})
        rng = jax.random.PRNGKey(0)
        # dense admission is bounded by free slots, so waves beyond
        # num_slots are dead shapes — don't pay their compiles (paged
        # prefill is slotless: any wave size can occur)
        sizes = [w for w in _WAVE_SIZES
                 if self.paged or w == 1 or w // 2 < self.num_slots]
        for bucket in buckets:
            for wave in sizes:
                if self.paged:
                    packed = np.zeros((wave, bucket + 2), np.int32)
                    packed[:, bucket] = 1
                    tables = jnp.zeros((wave, self.max_pages), jnp.int32)
                    _, self._cache = self._get_prefill_paged(
                        bucket, wave)(self.params, self._cache,
                                      jnp.asarray(packed), tables, rng)
                    continue
                packed = np.zeros((wave, bucket + 3), np.int32)
                packed[:, bucket] = 1
                packed[:, bucket + 1] = self.num_slots      # scratch
                firsts, pre, slots = self._get_prefill(bucket, wave)(
                    self.params, jnp.asarray(packed), rng)
                self._cache = self._get_insert(bucket, wave)(
                    self._cache, pre, slots)
        combined, self._state, self._cache = self._block_jit(
            self.params, self._cache, self._state, *self._no_admit)
        np.asarray(combined)   # force completion (and the compile)
        if burst and self.paged:
            import asyncio

            plen = max(prompt_lens)

            async def _burst():
                futs = [self.submit([7] * plen, max_new_tokens=1)
                        for _ in range(burst)]
                await asyncio.gather(*futs)

            # mirror submit()'s loop-aware dual path: asyncio.run()
            # raises inside a running event loop (an async serve replica
            # warming up from a coroutine), so drive the burst from a
            # helper thread that owns its own loop instead
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                asyncio.run(_burst())
            else:
                out: dict = {}

                def _runner():
                    try:
                        asyncio.run(_burst())
                    except BaseException as e:  # noqa: BLE001
                        out["err"] = e

                t = threading.Thread(target=_runner,
                                     name="llm-warmup-burst")
                t.start()
                t.join()
                if "err" in out:
                    raise out["err"]

    def submit(self, prompt: List[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None):
        """Enqueue one generation request.

        From inside a running event loop returns an awaitable resolving
        to a GenerationResult (async serve replicas); from a plain
        thread blocks and returns the result (drivers, benchmarks)."""
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(f"prompt len {len(prompt)} > max_prompt_len "
                             f"{self.max_prompt_len}")
        digests = (page_digests(prompt, self.page_size)
                   if self.paged and self.prefix_cache_pages else None)
        return self._submit_request(
            lambda deliver: _Request(list(prompt), max_new_tokens,
                                     temperature, eos_id, deliver,
                                     on_token, digests=digests),
            self._enqueue)

    async def stream(self, prompt: List[int], *, max_new_tokens: int = 32,
                     temperature: float = 0.0,
                     eos_id: Optional[int] = None):
        """Async-generator submit: yields each generated token id the
        scheduling quantum it is decoded, then the final
        GenerationResult as the last item.  This is the engine end of
        the Serve token-streaming path (serve/llm.py LLMServer.stream →
        replica handle_request_streaming → the caller's
        StreamingObjectRefGenerator): the consumer holds the first
        token while the block decode is still running.

        on_token callbacks fire on the engine thread and are bridged
        onto the calling event loop; the engine's completion delivery
        is loop-ordered after every bridged token, so the final result
        always follows the tokens it summarizes."""
        import asyncio
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_token(tok: int) -> None:
            loop.call_soon_threadsafe(q.put_nowait, ("token", int(tok)))

        fut = self.submit(prompt, max_new_tokens=max_new_tokens,
                          temperature=temperature, eos_id=eos_id,
                          on_token=on_token)
        fut.add_done_callback(lambda f: q.put_nowait(("done", f)))
        seen = 0
        while True:
            kind, val = await q.get()
            if kind == "token":
                seen += 1
                yield val
                continue
            # raylint: disable=async-blocking -- future already done (this item came from its add_done_callback); result() cannot block
            result = val.result()   # raises engine-fatal errors
            # backstop: any token whose bridge callback lost the race
            # with completion still reaches the consumer, in order
            for tok in result.tokens[seen:]:
                yield int(tok)
            yield result
            return

    # ------------------------------------------- disaggregated handoff API

    def export_prefill(self, prompt: List[int], *,
                       max_new_tokens: int = 32, temperature: float = 0.0,
                       eos_id: Optional[int] = None):
        """Prefill-only submit: run slotless paged prefill, sample the
        first token, then GATHER the request's pool pages into one
        contiguous host buffer and free them — the request never takes
        a decode slot here.  Resolves to a PrefillHandoff that
        ``import_prefill`` on another engine admits straight into
        decode.  Loop-aware like ``submit`` (awaitable inside an event
        loop, blocking from a plain thread)."""
        if not self.paged:
            raise RuntimeError("export_prefill requires paged=True")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(f"prompt len {len(prompt)} > max_prompt_len "
                             f"{self.max_prompt_len}")
        digests = (page_digests(prompt, self.page_size)
                   if self.prefix_cache_pages else None)
        return self._submit_request(
            lambda deliver: _Request(list(prompt), max_new_tokens,
                                     temperature, eos_id, deliver, None,
                                     export=True, digests=digests),
            self._enqueue)

    def import_prefill(self, handoff: PrefillHandoff, *,
                       on_token: Optional[Callable[[int], None]] = None):
        """Admit a PrefillHandoff exported elsewhere: allocate pool
        pages for the full generation span, scatter the shipped prompt
        K/V into them (one upload + page-table remap), and queue the
        request for a decode slot with its first token already known —
        no prefill runs here.  Resolves to the GenerationResult
        (``tokens[0]`` is the handoff's first token; ``on_token`` fires
        only for tokens decoded HERE — the exporter already delivered
        the first one).

        Admission is FIFO like pending prefills: an import whose pages
        aren't free yet WAITS in the engine (one deque entry — pages
        free as resident streams complete, so the wait cannot wedge).
        KVPoolFullError is raised SYNCHRONOUSLY only when
        ``import_queue_max`` is set and the wait queue is full — the
        signal for the router to re-queue against another replica."""
        if not self.paged:
            raise RuntimeError("import_prefill requires paged=True")
        h = handoff
        if h.finish_reason is not None:
            raise ValueError("handoff already finished at its first "
                             "token; nothing to decode")
        if h.page_size != self.page_size:
            raise ValueError(f"handoff page_size {h.page_size} != engine "
                             f"page_size {self.page_size}")
        kv = np.asarray(h.kv)
        if (kv.ndim != 5 or kv.shape[0] != self._ltot
                or kv.shape[1] != h.npages
                or tuple(kv.shape[2:]) != self._pool_tail):
            raise ValueError(
                f"handoff kv shape {kv.shape} does not match this "
                f"engine's pool layout [{self._ltot}, {h.npages}, "
                f"{self._pool_tail}] — engines must share the model "
                "config and page_size")
        if h.prompt_len >= self.cfg.max_seq_len:
            # an exporter with a larger max_seq_len can produce this;
            # it must fail THIS request, not broadcast-error inside the
            # engine loop (which would fail every resident request)
            raise ValueError(
                f"handoff prompt_len {h.prompt_len} >= this engine's "
                f"max_seq_len {self.cfg.max_seq_len}")
        span = min(h.prompt_len + h.max_new_tokens, self.cfg.max_seq_len)
        need = -(-span // self.page_size)
        if h.npages > need or h.npages > self.max_pages:
            raise ValueError(
                f"handoff ships {h.npages} pages but this engine's "
                f"span allows {min(need, self.max_pages)}")
        if need > self.kv_pool_pages - 1:
            raise ValueError(
                f"handoff needs {need} KV pages; pool holds "
                f"{self.kv_pool_pages - 1}")

        def build(deliver):
            req = _Request([], h.max_new_tokens, h.temperature, h.eos_id,
                           deliver, on_token)
            return _Import(h, req, need)

        def enqueue(imp):
            with self._lock:
                if self._closed:
                    raise RuntimeError("engine closed")
                if (self.import_queue_max is not None
                        and len(self._imports) >= self.import_queue_max):
                    # synchronous, so a full pool costs the caller ONE
                    # exception — not an engine-loop round trip
                    self.stats.import_rejects += 1
                    raise KVPoolFullError(
                        f"import wait queue full "
                        f"({len(self._imports)} >= "
                        f"{self.import_queue_max}); "
                        f"{len(self._free_pages)} pages free of "
                        f"{self.kv_pool_pages - 1}")
                self._imports.append(imp)
                if self._thread is None or not self._thread.is_alive():
                    self._thread = threading.Thread(target=self._loop,
                                                    daemon=True)
                    self._thread.start()
                self._lock.notify()

        return self._submit_request(build, enqueue)

    async def stream_import(self, handoff: PrefillHandoff):
        """Async-generator import: yields each token decoded HERE the
        quantum it lands (the handoff's first token is NOT re-yielded —
        the prefill side already streamed it), then the final
        GenerationResult.  Decode-pool end of the disaggregated
        streaming path (serve/llm.py LLMServer.decode)."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_token(tok: int) -> None:
            loop.call_soon_threadsafe(q.put_nowait, ("token", int(tok)))

        fut = self.import_prefill(handoff, on_token=on_token)
        fut.add_done_callback(lambda f: q.put_nowait(("done", f)))
        seen = 0
        while True:
            kind, val = await q.get()
            if kind == "token":
                seen += 1
                yield val
                continue
            # raylint: disable=async-blocking -- future already done (this item came from its add_done_callback); result() cannot block
            result = val.result()   # raises KVPoolFullError / fatal
            # tokens[0] is the handoff's first token; backstop any
            # decoded token whose bridge lost the race with completion
            for tok in result.tokens[1 + seen:]:
                yield int(tok)
            yield result
            return

    def _submit_request(self, build, enqueue):
        """The loop-aware dual delivery path shared by submit /
        export_prefill / import_prefill: build the queue item around a
        deliver callback, enqueue it, and return an awaitable (inside a
        running event loop) or block for the result."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            fut = loop.create_future()

            def deliver(ok, value, _loop=loop, _fut=fut):
                def _set():
                    if _fut.done():
                        return
                    (_fut.set_result if ok else _fut.set_exception)(value)
                _loop.call_soon_threadsafe(_set)

            enqueue(build(deliver))
            return fut
        ev = threading.Event()
        out: dict = {}

        def deliver(ok, value):
            out["ok" if ok else "err"] = value
            ev.set()

        enqueue(build(deliver))
        ev.wait()
        if "err" in out:
            raise out["err"]
        return out["ok"]

    def load_snapshot(self) -> dict:
        """Cheap queue/occupancy snapshot feeding per-pool autoscaling
        (serve/replica.py get_metrics -> controller): a prefill pool
        scales off queue depth, a decode pool off slot/ready occupancy."""
        with self._lock:
            return {
                "pending": len(self._pending),
                "imports": len(self._imports) if self.paged else 0,
                "ready": len(self._ready) if self.paged else 0,
                "busy_slots": self.num_slots - len(self._free),
                "free_pages": (len(self._free_pages) if self.paged
                               else 0),
                "pool_pages": self.kv_pool_pages if self.paged else 0,
                "prefix_pages_cached": (self._prefix_pages_used
                                        if self.paged else 0),
                "prefix_entries": (len(self._prefix_entries)
                                   if self.paged else 0),
            }

    def close(self):
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    # -------------------------------------------------------- engine loop

    def _enqueue(self, req: _Request):
        with self._lock:
            if self._closed:
                raise RuntimeError("engine closed")
            self._pending.append(req)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()
            self._lock.notify()

    def _bucket(self, n: int) -> int:
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.cfg.max_seq_len)

    def _wave_chunks(self, items: list):
        """Group (req, payload) pairs by prompt-length bucket and yield
        (bucket, chunk, wave_size) batches — the one admission-batching
        policy both the dense and the paged prefill paths follow."""
        by_bucket: dict = {}
        for item in items:
            by_bucket.setdefault(self._bucket(len(item[0].prompt)),
                                 []).append(item)
        for bucket, group in by_bucket.items():
            for start in range(0, len(group), _WAVE_SIZES[-1]):
                chunk = group[start:start + _WAVE_SIZES[-1]]
                wave = next(w for w in _WAVE_SIZES if w >= len(chunk))
                yield bucket, chunk, wave

    def _next_key(self):
        self._rng, key = jax.random.split(self._rng)
        return key

    def _dispatch_admission_wave(self, group: list, bucket: int,
                                 wave: int):
        """One batched prefill + one batched cache insert for admits
        sharing a prompt-length bucket.  Returns the DEVICE array of
        their first tokens — nothing is fetched here, and everything
        rides ONE packed upload (each host->device transfer is a
        round-trip on a remote-chip transport)."""
        # packed layout per row: [prompt(bucket) | s_real | slot | temp*1e6]
        packed = np.zeros((wave, bucket + 3), np.int32)
        packed[:, bucket] = 1
        packed[:, bucket + 1] = self.num_slots        # pad rows: scratch
        for r, (req, slot) in enumerate(group):
            packed[r, :len(req.prompt)] = req.prompt
            packed[r, bucket] = len(req.prompt)
            packed[r, bucket + 1] = slot
            packed[r, bucket + 2] = int(req.temperature * 1e6)
        firsts, pre_cache, slots = self._get_prefill(bucket, wave)(
            self.params, jnp.asarray(packed), self._next_key())
        self._cache = self._get_insert(bucket, wave)(
            self._cache, pre_cache, slots)
        self.stats.prefills += len(group)
        return firsts[:len(group)]

    def _finish_admit(self, req: _Request, slot: int, first: int):
        self.stats.tokens_generated += 1
        sl = _Slot(req, len(req.prompt), first)
        self._slots[slot] = sl
        if req.on_token is not None:
            self._safe_on_token(req, first)
        # a 1-token request (or instant eos) finishes without stepping
        self._maybe_finish(slot)

    def _safe_on_token(self, req: _Request, token: int):
        try:
            req.on_token(token)
        except Exception:       # user callback; never kills the loop
            pass

    @staticmethod
    def _safe_deliver(req: _Request, ok: bool, value) -> None:
        """Exactly-once, exception-proof completion: a client whose
        event loop already closed (or a fatal-path retry of an already
        completed request) must never poison the engine loop or steal
        other submitters' deliveries."""
        if req.delivered:
            return
        req.delivered = True
        try:
            req.deliver(ok, value)
        except Exception:
            pass

    @staticmethod
    def _finish_reason(sl: _Slot, max_seq_len: int) -> Optional[str]:
        req = sl.request
        if req.eos_id is not None and sl.last_token == req.eos_id:
            return "eos"
        if len(sl.out) >= req.max_new_tokens:
            return "length"
        if sl.pos + 1 >= max_seq_len:
            return "length"
        return None

    def _deliver_result(self, sl: _Slot, reason: str) -> None:
        req = sl.request
        now = time.monotonic()
        result = GenerationResult(
            tokens=sl.out, finish_reason=reason,
            prompt_len=sl.pos - len(sl.out) + 1,
            time_to_first_token_s=sl.first_token_at - req.submitted_at,
            latency_s=now - req.submitted_at)
        self.stats.requests_completed += 1
        self._safe_deliver(req, True, result)

    def _maybe_finish(self, i: int) -> bool:
        sl = self._slots[i]
        reason = self._finish_reason(sl, self.cfg.max_seq_len)
        if reason is None:
            return False
        self._slots[i] = None
        self._free.append(i)
        if self.paged:
            # the freed slot junk-steps its old table until its redirect
            # row rides a block dispatch; pages recycle only through
            # later dispatches, so immediate free is stream-safe (see
            # module docstring).  Junk writes only ever advance PAST the
            # prompt span, so leading pages retained by the prefix cache
            # are never touched by the straggling steps.
            self._stale_slots.add(i)
            self._prefix_release(sl)
        self._deliver_result(sl, reason)
        return True

    def _loop(self):
        if self.paged:
            return self._loop_paged()
        # Software-pipelined: quantum k+1 is DISPATCHED before quantum
        # k's results are fetched and processed, so the device never
        # idles on the host's fetch round-trip or bookkeeping.  The
        # price is a one-block admission/eviction lag, which the
        # request-identity checks in _process_quantum make safe.
        inflight = None
        while True:
            with self._lock:
                while (not self._closed and not self._pending
                       and all(s is None for s in self._slots)
                       and inflight is None):
                    self._lock.wait()
                if self._closed:
                    victims = ([s.request for s in self._slots
                                if s is not None]
                               + ([r for r, _ in inflight[1]]
                                  if inflight else [])
                               + list(self._pending))
                    self._pending.clear()
                    for req in victims:
                        self._safe_deliver(
                            req, False,
                            RuntimeError("engine closed"))
                    return
                admits = []
                while self._pending and self._free:
                    admits.append((self._pending.popleft(),
                                   self._free.pop()))
            try:
                nxt = self._dispatch_quantum(admits, inflight)
                if inflight is not None:
                    self._process_quantum(inflight)
                inflight = nxt
            except Exception as e:   # engine-fatal (OOM, compile error)
                with self._lock:
                    victims = ([s.request for s in self._slots
                                if s is not None]
                               + [a[0] for a in admits]
                               + ([r for r, _ in inflight[1]]
                                  if inflight else [])
                               + list(self._pending))
                    self._pending.clear()
                    self._slots = [None] * self.num_slots
                    self._free = list(range(self.num_slots))[::-1]
                inflight = None
                # the block/insert calls donate the cache and device
                # state: after a failed call the old buffers may be
                # deleted — rebuild before continuing
                self._cache = self._init_cache(self._rows)
                self._state = self._init_state(0)
                for req in victims:
                    self._safe_deliver(req, False, e)

    def _dispatch_quantum(self, admits: list, inflight):
        """Prefill + enqueue one decode block; returns (combined_device,
        admitted, rows) or None when there is nothing to run.  ``rows``
        snapshots (slot_index, request) pairs whose tokens this block
        carries — including the PREVIOUS quantum's admissions, which are
        decoding on device but not yet placed in _slots."""
        admitted = []                      # (req, slot) in firsts order
        firsts_parts = []
        for bucket, chunk, wave in self._wave_chunks(admits):
            firsts_parts.append(
                self._dispatch_admission_wave(chunk, bucket, wave))
            admitted.extend(chunk)

        rows = [(i, s.request) for i, s in enumerate(self._slots)
                if s is not None]
        if inflight is not None:
            rows += [(slot, req) for req, slot in inflight[1]]
        rows += [(slot, req) for req, slot in admitted]
        if not rows:
            return None
        # decode state (tokens/positions/temps/rng) is device-chained;
        # the host uploads one packed admit array, cached when empty
        n_admit = len(admitted)
        if n_admit:
            A = self.num_slots
            meta = np.zeros((3, A), np.int32)
            meta[0, :] = self.num_slots
            for r, (req, slot) in enumerate(admitted):
                meta[0, r] = slot
                meta[1, r] = len(req.prompt)
                meta[2, r] = int(req.temperature * 1e6)
            pad = jnp.zeros((A - n_admit,), jnp.int32)
            admit_meta = jnp.asarray(meta)
            admit_firsts = jnp.concatenate(firsts_parts + [pad])
        else:
            admit_meta, admit_firsts = self._no_admit
        combined, self._state, self._cache = self._block_jit(
            self.params, self._cache, self._state, admit_meta,
            admit_firsts)
        return (combined, admitted, rows)

    def _process_quantum(self, quantum):
        combined, admitted, rows = quantum
        host = np.asarray(combined)        # the ONE fetch this quantum
        K = self.block_size
        block = host[:self._rows * K].reshape(self._rows, K)
        self.stats.steps += K

        # --- admissions complete (their first tokens are now known) ---
        for (req, slot), first in zip(admitted, host[self._rows * K:]):
            self._finish_admit(req, slot, int(first))
        # --- block processing: truncate junk past each row's finish ---
        for i, req in rows:
            sl = self._slots[i]
            if sl is None or sl.request is not req:
                continue      # evicted earlier (or reused): junk row
            for k in range(K):
                tok = int(block[i, k])
                sl.out.append(tok)
                sl.last_token = tok
                sl.pos += 1
                self.stats.step_tokens += 1
                self.stats.tokens_generated += 1
                if sl.request.on_token is not None:
                    self._safe_on_token(sl.request, tok)
                if self._maybe_finish(i):
                    break     # rest of the row is junk past eos

    # ------------------------------------------------- prompt-prefix cache
    #
    # All mutation happens on the engine loop thread; _prefix_lock only
    # makes the index/entry maps readable from RPC threads
    # (prefix_digests, load_snapshot).  Pages owned by the cache are in
    # NEITHER _free_pages nor any slot: retention moves ownership from a
    # finishing slot to an entry, eviction moves it back to the free
    # list.  The _free_pages list itself stays loop-thread-confined.

    def prefix_digests(self, limit: int = 64) -> List[str]:
        """Boundary digests of retained prefix runs, newest entries
        first — the replica's advertisement on the controller
        load-publish path (frontdoor/prefix.py contract)."""
        if not (self.paged and self.prefix_cache_pages):
            return []
        out: List[str] = []
        with self._prefix_lock:
            for entry in reversed(self._prefix_entries.values()):
                take = entry.chain[:len(entry.pages)]
                rest = max(0, limit - len(out))
                out.extend(take[-rest:] if rest < len(take) else take)
                if len(out) >= limit:
                    break
        return out[:limit]

    def _prefix_lookup(self, req: _Request):
        """Deepest retained run covering a page-aligned prefix of
        ``req.prompt`` (loop thread, engine lock held).  Returns
        (entry, cover_pages) or None; the hit must leave >= 1 suffix
        token to prefill (it samples the first token) and the padded
        suffix window must still fit max_seq_len."""
        digests = req.digests
        if not digests or not self.prefix_cache_pages:
            return None
        # never borrow the page holding the last prompt token: at least
        # one real token must run through the suffix prefill
        max_cover = (len(req.prompt) - 1) // self.page_size
        with self._prefix_lock:
            for i in range(min(len(digests), max_cover) - 1, -1, -1):
                found = self._prefix_index.get(digests[i])
                if found is None:
                    continue
                entry, cover = found
                cover = min(cover, max_cover, len(entry.pages))
                if cover <= 0:
                    continue
                suffix = len(req.prompt) - cover * self.page_size
                if (cover * self.page_size + self._bucket(suffix)
                        > self.cfg.max_seq_len):
                    continue   # padded window would overflow the span
                entry.refs += 1
                self._prefix_seq += 1
                entry.last_used = self._prefix_seq
                self._prefix_entries.move_to_end(entry.chain[-1])
                return entry, cover
        return None

    def _prefix_evict_locked(self, need: int) -> bool:
        """Evict refs==0 entries, oldest first, until ``need`` cache-
        budget pages are free.  Evicted pages return to _free_pages.
        Caller holds _prefix_lock; loop thread only."""
        if need > self.prefix_cache_pages:
            return False
        victims = [e for e in self._prefix_entries.values()
                   if e.refs == 0]
        vi = 0
        while (self._prefix_pages_used + need > self.prefix_cache_pages
               and vi < len(victims)):
            entry = victims[vi]
            vi += 1
            for d in entry.chain:
                if self._prefix_index.get(d, (None,))[0] is entry:
                    del self._prefix_index[d]
            self._prefix_entries.pop(entry.chain[-1], None)
            self._prefix_pages_used -= len(entry.pages)
            self._free_pages.extend(entry.pages)
            entry.pages = []
            self.stats.prefix_evictions += 1
        return self._prefix_pages_used + need <= self.prefix_cache_pages

    def _prefix_reclaim(self, need_free: int) -> None:
        """Admission pressure valve (loop thread, engine lock held):
        the FIFO head needs ``need_free`` pages the free list doesn't
        have — evict idle retained runs to unblock it rather than
        wedging admission behind the cache."""
        if not self.prefix_cache_pages:
            return
        with self._prefix_lock:
            freed = 0
            for key in list(self._prefix_entries):
                if freed >= need_free:
                    break
                entry = self._prefix_entries[key]
                if entry.refs:
                    continue
                for d in entry.chain:
                    if self._prefix_index.get(d, (None,))[0] is entry:
                        del self._prefix_index[d]
                del self._prefix_entries[key]
                self._prefix_pages_used -= len(entry.pages)
                self._free_pages.extend(entry.pages)
                freed += len(entry.pages)
                entry.pages = []
                self.stats.prefix_evictions += 1

    def _prefix_retain(self, sl: _Slot) -> int:
        """Move a finishing slot's leading full PROMPT pages into the
        cache (loop thread).  Returns how many of sl.pages the cache
        took (they must not be freed); 0 when retention is off, the
        prompt spans < 1 full page, the run is already cached, or the
        budget cannot fit it even after eviction."""
        req = sl.request
        if (not self.prefix_cache_pages or not req.digests
                or sl.borrowed):
            return 0
        n_full = min(sl.prompt_len // self.page_size, len(req.digests),
                     len(sl.pages))
        if n_full <= 0:
            return 0
        chain = req.digests[:n_full]
        with self._prefix_lock:
            known = self._prefix_index.get(chain[-1])
            if known is not None and known[1] >= n_full:
                return 0                    # already resident
            if not self._prefix_evict_locked(n_full):
                return 0
            entry = _PrefixEntry(sl.pages[:n_full], chain)
            self._prefix_seq += 1
            entry.last_used = self._prefix_seq
            for i, d in enumerate(chain):
                self._prefix_index[d] = (entry, i + 1)
            self._prefix_entries[chain[-1]] = entry
            self._prefix_entries.move_to_end(chain[-1])
            self._prefix_pages_used += n_full
        return n_full

    def _prefix_release(self, sl: _Slot) -> None:
        """Free a paged slot's pages with prefix accounting: borrowed
        prefix pages go back to their entry (refcount), owned pages are
        offered to retention first, the rest return to the pool."""
        kept = self._prefix_retain(sl)
        self._free_pages.extend(sl.pages[max(kept, sl.borrowed):])
        if sl.prefix_entry is not None:
            with self._prefix_lock:
                sl.prefix_entry.refs -= 1
            sl.prefix_entry = None
        sl.pages = []
        sl.borrowed = 0

    def _prefix_reset(self) -> None:
        """Engine-fatal recovery: the pool was rebuilt, every retained
        page id is meaningless — drop the cache wholesale."""
        if not self.paged:
            return
        with self._prefix_lock:
            self._prefix_index.clear()
            self._prefix_entries.clear()
            self._prefix_pages_used = 0

    # ---------------------------------------------------- paged engine loop

    def _pages_needed(self, req: _Request) -> int:
        if req.export:
            # prefill-only: the request never decodes here, so it holds
            # exactly its prompt's pages until the export gather frees
            # them (the importer allocates the full span)
            return -(-len(req.prompt) // self.page_size)
        span = min(len(req.prompt) + req.max_new_tokens,
                   self.cfg.max_seq_len)
        return -(-span // self.page_size)

    def _loop_paged(self):
        """Pipelined like _loop, with a slotless prefill stage ahead of
        the block: each iteration (1) prefills as many queued prompts as
        the pool allows, (2) installs ready requests into free slots and
        dispatches the next block, (3) processes the PREVIOUS block's
        fetch, (4) fetches this iteration's prefill first-tokens (the
        device finished them before the just-dispatched block).  TTFT is
        therefore one prefill round-trip, independent of slot turnover.
        """
        inflight = None       # (combined_dev, rows)
        while True:
            with self._lock:
                while (not self._closed and not self._pending
                       and not self._imports and not self._ready
                       and all(s is None for s in self._slots)
                       and inflight is None):
                    self._lock.wait()
                if self._closed:
                    victims = (
                        [s.request for s in self._slots if s is not None]
                        + [pf.slot_state.request for pf in self._ready]
                        + [imp.request for imp in self._imports]
                        + list(self._pending))
                    self._pending.clear()
                    self._ready.clear()
                    self._imports.clear()
                    for req in victims:
                        self._safe_deliver(
                            req, False, RuntimeError("engine closed"))
                    return
                # imports first (a decode-pool engine's whole intake is
                # handoffs), FIFO like pending prefills: the head waits
                # for pages, nothing bypasses it (no starvation), and
                # pages always free as resident streams complete — the
                # queue-full rejection happens synchronously at submit
                import_todo = []
                while self._imports:
                    short = self._imports[0].need - len(self._free_pages)
                    if short > 0:
                        # idle retained prefixes must not wedge the
                        # FIFO head: the cache yields before admission
                        self._prefix_reclaim(short)
                    if self._imports[0].need > len(self._free_pages):
                        break
                    imp = self._imports.popleft()
                    pages = [self._free_pages.pop()
                             for _ in range(imp.need)]
                    import_todo.append((imp, pages))
                todo = []
                hits = []
                oversized = []
                while self._pending:
                    need = self._pages_needed(self._pending[0])
                    if need > self.kv_pool_pages - 1:
                        # can never fit: fail it rather than spin forever
                        oversized.append(self._pending.popleft())
                        continue
                    hit = self._prefix_lookup(self._pending[0])
                    fresh = need - (hit[1] if hit else 0)
                    if fresh > len(self._free_pages):
                        self._prefix_reclaim(
                            fresh - len(self._free_pages))
                    if fresh > len(self._free_pages):
                        if hit is not None:
                            with self._prefix_lock:
                                hit[0].refs -= 1
                        break          # FIFO: no bypass, no starvation
                    req = self._pending.popleft()
                    pages = [self._free_pages.pop()
                             for _ in range(fresh)]
                    if hit is not None:
                        entry, cover = hit
                        self.stats.prefix_hits += 1
                        self.stats.prefix_tokens_saved += \
                            cover * self.page_size
                        hits.append((req, entry.pages[:cover] + pages,
                                     cover, entry))
                    else:
                        if self.prefix_cache_pages and req.digests:
                            self.stats.prefix_misses += 1
                        todo.append((req, pages))
            for req in oversized:
                self._safe_deliver(req, False, ValueError(
                    f"request needs {self._pages_needed(req)} KV pages; "
                    f"pool holds {self.kv_pool_pages - 1}"))
            try:
                # scatter imports BEFORE taking installs: an imported
                # request can land in a free slot this same iteration,
                # and the block step is dispatched after the scatter so
                # stream order covers its page writes
                self._dispatch_import_waves(import_todo)
                with self._lock:
                    installs = []
                    while self._free and self._ready:
                        installs.append((self._ready.popleft(),
                                         self._free.pop()))
                new_prefills = (self._dispatch_prefill_waves(todo)
                                + self._dispatch_suffix_waves(hits))
                nxt = self._dispatch_block_paged(installs)
                if inflight is not None:
                    self._process_block_paged(inflight)
                self._process_exports(
                    self._process_prefill_waves(new_prefills))
                inflight = nxt
            except Exception as e:   # engine-fatal (OOM, compile error)
                with self._lock:
                    victims = (
                        [s.request for s in self._slots if s is not None]
                        + [pf.slot_state.request for pf in self._ready]
                        + [r for r, _ in todo]
                        + [r for r, _, _, _ in hits]
                        + [imp.request for imp, _ in import_todo]
                        + [imp.request for imp in self._imports]
                        + ([r for _, r in inflight[1]] if inflight else [])
                        + list(self._pending))
                    self._pending.clear()
                    self._ready.clear()
                    self._imports.clear()
                    self._slots = [None] * self.num_slots
                    self._free = list(range(self.num_slots))[::-1]
                    self._free_pages = list(
                        range(1, self.kv_pool_pages))[::-1]
                    self._stale_slots.clear()
                self._prefix_reset()
                inflight = None
                self._cache = self._init_cache(self._rows)
                self._state = self._init_state(0)
                for req in victims:
                    self._safe_deliver(req, False, e)

    def _dispatch_prefill_waves(self, todo: list) -> list:
        """Batch queued prompts into (bucket, wave) prefill calls that
        write straight into their reserved pages.  Device dispatch only —
        first tokens are fetched later in the iteration."""
        out = []
        for bucket, chunk, wave in self._wave_chunks(todo):
            packed = np.zeros((wave, bucket + 2), np.int32)
            packed[:, bucket] = 1
            tables = np.zeros((wave, self.max_pages), np.int32)
            metas = []
            for r, (req, pages) in enumerate(chunk):
                packed[r, :len(req.prompt)] = req.prompt
                packed[r, bucket] = len(req.prompt)
                packed[r, bucket + 1] = int(req.temperature * 1e6)
                tables[r, :len(pages)] = pages
                metas.append((req, pages, tables[r].copy(), 0, None))
            firsts, self._cache = self._get_prefill_paged(
                bucket, wave)(self.params, self._cache,
                              jnp.asarray(packed),
                              jnp.asarray(tables), self._next_key())
            self.stats.prefills += len(chunk)
            out.append((firsts, metas))
        return out

    def _dispatch_suffix_waves(self, todo: list) -> list:
        """Prefix-cache hits: batch by SUFFIX-length bucket and run the
        offset prefill — each row's leading table entries are borrowed
        read-only prefix pages, the window starts at the page-aligned
        cover and writes only fresh pages.  Output rides the same
        (firsts, metas) shape as _dispatch_prefill_waves."""
        out = []
        by_bucket: dict = {}
        for item in todo:
            req, pages, cover, entry = item
            sfx = len(req.prompt) - cover * self.page_size
            by_bucket.setdefault(self._bucket(sfx), []).append(item)
        for bucket, group in by_bucket.items():
            for start in range(0, len(group), _WAVE_SIZES[-1]):
                chunk = group[start:start + _WAVE_SIZES[-1]]
                wave = next(w for w in _WAVE_SIZES if w >= len(chunk))
                packed = np.zeros((wave, bucket + 2), np.int32)
                packed[:, bucket] = 1
                tables = np.zeros((wave, self.max_pages), np.int32)
                offs = np.zeros((wave,), np.int32)
                metas = []
                for r, (req, pages, cover, entry) in enumerate(chunk):
                    c = cover * self.page_size
                    suffix = req.prompt[c:]
                    packed[r, :len(suffix)] = suffix
                    packed[r, bucket] = len(suffix)
                    packed[r, bucket + 1] = int(req.temperature * 1e6)
                    tables[r, :len(pages)] = pages
                    offs[r] = c
                    metas.append((req, pages, tables[r].copy(),
                                  cover, entry))
                firsts, self._cache = self._get_prefill_suffix(
                    bucket, wave)(self.params, self._cache,
                                  jnp.asarray(packed),
                                  jnp.asarray(tables),
                                  jnp.asarray(offs), self._next_key())
                self.stats.prefills += len(chunk)
                out.append((firsts, metas))
        return out

    def _process_prefill_waves(self, waves: list) -> list:
        """Fetch this iteration's prefill first-tokens with ONE combined
        device->host transfer (each fetch is a full round-trip on a
        remote-chip transport; a saturation burst dispatches many waves
        per iteration) and complete/queue each request.  Returns the
        export-flagged requests (first token now known) for
        _process_exports."""
        if not waves:
            return []
        if len(waves) == 1:
            host = np.asarray(waves[0][0])
        else:
            host = np.asarray(jnp.concatenate([f for f, _ in waves]))
        off = 0
        exports = []
        for firsts, metas in waves:
            n = firsts.shape[0]
            exports.extend(self._complete_prefills(metas,
                                                   host[off:off + n]))
            off += n
        return exports

    def _complete_prefills(self, metas, host) -> list:
        """Requests finish here if one token was all they wanted,
        otherwise they join the ready queue holding their first token.
        Export-flagged requests are returned for the gather stage
        instead of queueing for a local slot."""
        exports = []
        for (req, pages, table, borrowed, entry), first in \
                zip(metas, host):
            self.stats.tokens_generated += 1
            sl = _Slot(req, len(req.prompt), int(first), pages,
                       borrowed, entry)
            if req.export:
                exports.append((req, sl))
                continue
            if req.on_token is not None:
                self._safe_on_token(req, int(first))
            reason = self._finish_reason(sl, self.cfg.max_seq_len)
            if reason is not None:
                # never installed -> nothing junk-steps these pages:
                # free immediately, no redirect needed
                self._prefix_release(sl)
                self._deliver_result(sl, reason)
            else:
                with self._lock:
                    self._ready.append(_Prefilled(sl, table))
        return exports

    def _process_exports(self, exports: list) -> None:
        """Gather exported requests' occupied pages into contiguous
        host buffers — one device dispatch + ONE fetch per
        (page-bucket, wave) group — free the pages, and deliver
        PrefillHandoffs.  Dispatched after this iteration's block step,
        so the gather reads the chained cache in stream order; the
        freed pages recycle only through later dispatches (the standard
        pool invariant)."""
        if not exports:
            return
        groups: dict = {}
        for req, sl in exports:
            reason = self._finish_reason(sl, self.cfg.max_seq_len)
            if reason is not None:
                # done at its first token: nothing to decode anywhere —
                # ship a kv-less handoff the serving layer completes
                # from directly
                self._prefix_release(sl)
                self.stats.requests_completed += 1
                self.stats.exports += 1
                self._safe_deliver(req, True, PrefillHandoff(
                    kv=None, page_size=self.page_size, npages=0,
                    prompt_len=sl.pos, first_token=sl.out[0],
                    max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature, eos_id=req.eos_id,
                    finish_reason=reason))
                continue
            n_occ = -(-sl.pos // self.page_size)   # prompt pages only
            groups.setdefault(self._page_bucket(n_occ),
                              []).append((req, sl, n_occ))
        for bucket, group in groups.items():
            for start in range(0, len(group), _WAVE_SIZES[-1]):
                chunk = group[start:start + _WAVE_SIZES[-1]]
                wave = next(w for w in _WAVE_SIZES if w >= len(chunk))
                t0 = time.monotonic()
                idx = np.zeros((wave, bucket), np.int32)
                for r, (req, sl, n_occ) in enumerate(chunk):
                    idx[r, :n_occ] = sl.pages[:n_occ]
                dev = self._get_export(bucket, wave)(self._cache,
                                                     jnp.asarray(idx))
                host = np.asarray(dev)             # ONE fetch per group
                # amortized per request (the import side divides its
                # wave cost the same way — the stages must be
                # comparable in the handoff-latency histogram)
                ms = round((time.monotonic() - t0) * 1e3 / len(chunk), 3)
                for r, (req, sl, n_occ) in enumerate(chunk):
                    kv = np.ascontiguousarray(host[r, :, :n_occ])
                    # the gather above already read the pages: retention
                    # (prefill-pool hot path) or free, borrow-aware
                    self._prefix_release(sl)
                    self.stats.exports += 1
                    self._safe_deliver(req, True, PrefillHandoff(
                        kv=kv, page_size=self.page_size, npages=n_occ,
                        prompt_len=sl.pos, first_token=sl.out[0],
                        max_new_tokens=req.max_new_tokens,
                        temperature=req.temperature, eos_id=req.eos_id,
                        export_ms=ms))

    def _dispatch_import_waves(self, todo: list) -> None:
        """Scatter admitted handoffs' prompt K/V into their freshly
        allocated pages — one packed upload + one jitted remap per
        (page-bucket, wave) group — and queue them ready-to-install
        with their first token already known.  No prefill compute, no
        fetch: the decode-only admission path."""
        if not todo:
            return
        groups: dict = {}
        for imp, pages in todo:
            groups.setdefault(self._page_bucket(imp.handoff.npages),
                              []).append((imp, pages))
        for bucket, group in groups.items():
            for start in range(0, len(group), _WAVE_SIZES[-1]):
                chunk = group[start:start + _WAVE_SIZES[-1]]
                wave = next(w for w in _WAVE_SIZES if w >= len(chunk))
                t0 = time.monotonic()
                kvbuf = np.zeros(
                    (wave, self._ltot, bucket) + self._pool_tail,
                    dtype=self.cfg.dtype)
                idx = np.zeros((wave, bucket), np.int32)
                for r, (imp, pages) in enumerate(chunk):
                    h = imp.handoff
                    kvbuf[r, :, :h.npages] = np.asarray(h.kv)
                    idx[r, :h.npages] = pages[:h.npages]
                self._cache = self._get_import(bucket, wave)(
                    self._cache, jnp.asarray(kvbuf), jnp.asarray(idx))
                if self.on_import_admit is not None:
                    ms = (time.monotonic() - t0) * 1e3 / len(chunk)
                    for _ in chunk:
                        self.on_import_admit(ms)
                for imp, pages in chunk:
                    h = imp.handoff
                    sl = _Slot(imp.request, h.prompt_len, h.first_token,
                               pages)
                    self.stats.imports += 1
                    reason = self._finish_reason(sl, self.cfg.max_seq_len)
                    if reason is not None:
                        # belt-and-braces: a 1-token import finishes
                        # without ever stepping (exporters normally
                        # short-circuit these with finish_reason)
                        self._free_pages.extend(sl.pages)
                        sl.pages = []
                        self._deliver_result(sl, reason)
                        continue
                    table = np.zeros((self.max_pages,), np.int32)
                    table[:len(pages)] = pages
                    with self._lock:
                        self._ready.append(_Prefilled(sl, table))

    def _dispatch_block_paged(self, installs: list):
        """Install ready requests into free slots (their last token and
        position are host-known — nothing is fetched), attach redirect
        rows for stale slots, and dispatch one decode block.  Returns
        (combined_device, rows) or None when no slot is active."""
        A = self.num_slots
        meta = np.zeros((3, A), np.int32)
        meta[0, :] = A                                  # pad -> scratch
        lasts = np.zeros((A,), np.int32)
        tables = np.zeros((A, self.max_pages), np.int32)
        n = 0
        for pf, slot in installs:
            sl = pf.slot_state
            self._slots[slot] = sl
            self._stale_slots.discard(slot)   # reuse doubles as redirect
            meta[0, n] = slot
            meta[1, n] = sl.pos
            meta[2, n] = int(sl.request.temperature * 1e6)
            lasts[n] = sl.last_token
            tables[n] = pf.table
            n += 1
        if all(s is None for s in self._slots):
            return None        # nothing to decode; redirects can wait
        for slot in sorted(self._stale_slots):
            if self._slots[slot] is None and n < A:
                meta[0, n] = slot   # zero token/pos/table -> scratch page
                n += 1
                self._stale_slots.discard(slot)
        admit = ((jnp.asarray(meta), jnp.asarray(lasts),
                  jnp.asarray(tables)) if n else self._no_admit)
        combined, self._state, self._cache = self._block_jit(
            self.params, self._cache, self._state, *admit)
        rows = [(i, s.request) for i, s in enumerate(self._slots)
                if s is not None]
        return (combined, rows)

    def _process_block_paged(self, quantum) -> None:
        combined, rows = quantum
        host = np.asarray(combined)        # the ONE fetch this quantum
        K = self.block_size
        block = host.reshape(self._rows, K)
        self.stats.steps += K
        for i, req in rows:
            sl = self._slots[i]
            if sl is None or sl.request is not req:
                continue      # evicted earlier (or reused): junk row
            for k in range(K):
                tok = int(block[i, k])
                sl.out.append(tok)
                sl.last_token = tok
                sl.pos += 1
                self.stats.step_tokens += 1
                self.stats.tokens_generated += 1
                if sl.request.on_token is not None:
                    self._safe_on_token(sl.request, tok)
                if self._maybe_finish(i):
                    break     # rest of the row is junk past eos
