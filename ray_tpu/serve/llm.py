"""Serve deployment for LLM generation on TPU replicas.

The north-star serving shape (BASELINE.md: "Serve llama-3-8b, TPU
replicas"): each replica owns one chip-resident LLMEngine
(serve/llm_engine.py, continuous batching over KV-cache slots) and an
async ``__call__`` that admits the request and awaits its completion —
concurrent Serve requests interleave at token granularity inside one
replica, and `num_replicas` scales across chips/hosts like any other
deployment.

Reference analog: `python/ray/serve` has no LLM-aware deployment; its
LLM benchmarks drive plain replicas.  This module is where the TPU
framework goes past parity.

Usage::

    from ray_tpu import serve
    app = serve.llm.build_app(preset="gpt-small", num_slots=8)
    handle = serve.run(app)
    out = ray_tpu.get(handle.remote({"prompt": [1, 2, 3],
                                     "max_new_tokens": 16}))
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private.config import CONFIG
from ray_tpu.serve.deployment import deployment
from ray_tpu.util.tracing import tracing_helper as trh

# Disaggregated-serving telemetry (docs/serve_disagg.md): per-pool
# latency families ("prefill"/"decode" pool labels; "colocated" for a
# classic single-pool replica) + handoff movement cost by stage.
_M_TTFT = rtm.histogram_family(
    "ray_tpu_serve_ttft_ms",
    "LLM time-to-first-token per pool (ms): submit -> first sampled "
    "token on the serving replica", tag_key="pool")
_M_TPOT = rtm.histogram_family(
    "ray_tpu_serve_tpot_ms",
    "LLM inter-token latency per pool (ms/token past the first)",
    tag_key="pool")
_M_HANDOFF_BYTES = rtm.histogram_family(
    "ray_tpu_serve_handoff_bytes",
    "paged-KV handoff object size per stage (export=gather+put, "
    "import=pull+scatter)", tag_key="stage",
    boundaries=(1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20,
                1 << 22, 1 << 24, 1 << 26, 1 << 28))
_M_HANDOFF_MS = rtm.histogram_family(
    "ray_tpu_serve_handoff_ms",
    "paged-KV handoff latency per stage (ms): export_gather (device "
    "gather+fetch), export_put (store publish), import_pull (transfer-"
    "plane fetch), import_admit (upload+remap until decode-ready)",
    tag_key="stage")
_M_HANDOFF_SAVED = rtm.counter(
    "ray_tpu_serve_handoff_saved_bytes",
    "cross-host KV handoff bytes NOT shipped thanks to the int8 wire "
    "codec (raw - encoded, serve_handoff_quantize)")

# one int8 wire-codec block size for both handoff endpoints: encode and
# decode must derive identical segmentation (quant.py wire layout)
_QUANT_BLOCK = 256


def _np_dtype(name: str):
    """np.dtype from its saved string, accepting jax's ml_dtypes names
    (a bf16 KV pool round-trips through the codec as bfloat16)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _encode_handoff(h):
    """Swap a PrefillHandoff's raw KV array for its int8 wire encoding
    (block-scaled symmetric, collective/quant.py): ~3.9x fewer bytes
    cross the object store + transfer plane per handoff."""
    from ray_tpu.util.collective.quant import get_codec
    raw = h.kv
    h.kv = get_codec("int8", _QUANT_BLOCK).encode(raw)
    h.codec = "int8"
    h.kv_shape = tuple(raw.shape)
    h.kv_dtype = str(raw.dtype)
    h.raw_nbytes = int(raw.nbytes)
    return h


def _decode_handoff(h):
    """Inverse of ``_encode_handoff``: restore the raw KV layout before
    the decode engine imports it (the engine never sees wire bytes)."""
    from ray_tpu.util.collective.quant import get_codec
    nelem = 1
    for dim in h.kv_shape:
        nelem *= int(dim)
    h.kv = get_codec(h.codec, _QUANT_BLOCK).decode(
        h.kv, nelem, _np_dtype(h.kv_dtype)).reshape(h.kv_shape)
    h.codec = None
    return h


def _record_handoff_event(stage: str, object_hex: str, nbytes: int,
                          dur_ms: float, **extra) -> None:
    """HANDOFF timeline slice (docs/observability.md): rides a synthetic
    ``handoff-<object>`` record like collective ops ride ``col-*`` —
    stamped with THIS process's node/worker ids so export and import
    slices land on their own pools' rows in Perfetto."""
    try:
        from ray_tpu.runtime.core_worker import get_global_worker
        w = get_global_worker()
        w.events.record(
            f"handoff-{object_hex[:16]}", "HANDOFF", name="kv_handoff",
            stage=stage, bytes=int(nbytes),
            dur_ms=round(float(dur_ms), 3), node_id=w.node_id,
            worker_id=w.worker_id.hex(), **extra)
    except Exception:
        pass  # observability only; never fails the request path


class LLMServer:
    """Replica class: one engine per replica, admission via async call.

    ``checkpoint``: optional orbax/train checkpoint directory holding
    ``params``; absent means randomly initialized weights (shape-correct
    perf benchmarking without a weights file).

    ``role``: ``"colocated"`` (default — one engine prefills AND
    decodes), ``"prefill"`` (serves ``prefill()`` handoff exports only)
    or ``"decode"`` (admits handoffs via ``decode()``, never prefills).
    The split pools of a ``disaggregated=True`` app (docs/
    serve_disagg.md); both split roles force ``paged=True``.
    """

    def __init__(self, preset: str = "tiny", *, num_slots: int = 8,
                 checkpoint: Optional[str] = None,
                 max_prompt_len: Optional[int] = None,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 block_size: int = 32, max_seq_len: Optional[int] = None,
                 warmup_prompt_lens: Optional[list] = None,
                 warmup_burst: int = 0,
                 paged: bool = False, page_size: int = 64,
                 kv_pool_pages: Optional[int] = None,
                 role: str = "colocated",
                 # deliberately SHORTER than DisaggHandle's
                 # pool_full_timeout_s (30s): the replica absorbs brief
                 # page pressure in-process, then the rejection escapes
                 # so the router can try another replica with pool
                 # headroom — equal timeouts would make the re-route
                 # path unreachable
                 import_retry_s: float = 5.0,
                 import_queue_max: Optional[int] = None,
                 prefix_cache_pages: Optional[int] = None,
                 _upstream: Any = None,
                 config_overrides: Optional[Dict[str, Any]] = None):
        from ray_tpu.models.configs import get_config
        from ray_tpu.serve.llm_engine import LLMEngine

        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(f"unknown LLMServer role {role!r}")
        self.role = role
        self.import_retry_s = import_retry_s
        del _upstream   # deploy-ordering anchor only (build_app)
        if role != "colocated":
            paged = True      # handoff is defined on the paged pool
        cfg = get_config(preset, **(config_overrides or {}))
        params = self._load_params(cfg, checkpoint, seed)
        if prefix_cache_pages is None:
            prefix_cache_pages = CONFIG.serve_prefix_cache_pages
        self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                max_prompt_len=max_prompt_len,
                                top_k=top_k, top_p=top_p, seed=seed,
                                block_size=block_size,
                                max_seq_len=max_seq_len, paged=paged,
                                page_size=page_size,
                                kv_pool_pages=kv_pool_pages,
                                import_queue_max=import_queue_max,
                                prefix_cache_pages=prefix_cache_pages)
        # exported handoff objects are owned by THIS replica: freeing
        # the last owner-side ref frees the object, so each ref is
        # pinned for a TTL comfortably beyond any decode retry deadline
        # (expired pins are swept on later prefill calls).  Memory is
        # bounded by in-flight handoffs x TTL — the inherent floor: the
        # object must outlive its pull.
        self._handoff_pins: collections.deque = collections.deque()
        self._handoff_pin_ttl_s = 180.0
        if role == "decode":
            # per-wave host-side remap cost (upload + scatter dispatch)
            self.engine.on_import_admit = (
                lambda ms: _M_HANDOFF_MS.observe("import_admit", ms))
        if warmup_prompt_lens:
            # pay all compiles at replica start, none at request time
            # (warmup_burst additionally compiles the paged engine's
            # saturation-burst fetch shapes — see LLMEngine.warmup)
            self.engine.warmup(prompt_lens=warmup_prompt_lens,
                               burst=warmup_burst)

    @staticmethod
    def _load_params(cfg, checkpoint: Optional[str], seed: int):
        from ray_tpu.models.gpt import GPT
        if checkpoint:
            from ray_tpu.air.checkpoint import Checkpoint
            ckpt = Checkpoint.from_directory(checkpoint)
            state = ckpt.to_dict()
            for key in ("params", "model_params"):
                if key in state:
                    return state[key]
            raise ValueError(
                f"checkpoint at {checkpoint} has no 'params' entry "
                f"(keys: {sorted(state)})")
        model = GPT(cfg, decode=True)
        tokens = jnp.zeros((1, 1), jnp.int32)
        return model.init(jax.random.PRNGKey(seed), tokens)["params"]

    @staticmethod
    async def _chain_first(first, agen):
        yield first
        async for item in agen:
            yield item

    def _observe_latency(self, ttft_s: float, latency_s: float,
                         ntokens: int) -> None:
        _M_TTFT.observe(self.role, ttft_s * 1e3)
        if ntokens > 1:
            _M_TPOT.observe(self.role,
                            (latency_s - ttft_s) * 1e3 / (ntokens - 1))

    async def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        prompt = request["prompt"]
        result = await self.engine.submit(
            prompt,
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"))
        self._observe_latency(result.time_to_first_token_s,
                              result.latency_s, len(result.tokens))
        return {
            "tokens": result.tokens,
            "finish_reason": result.finish_reason,
            "prompt_len": result.prompt_len,
            "time_to_first_token_s": result.time_to_first_token_s,
            "latency_s": result.latency_s,
        }

    # ------------------------------------------ disaggregated pool methods

    async def prefill(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Prefill-pool entrypoint: run slotless paged prefill, export
        the request's KV pages + sampled first token as ONE handoff
        object published via ``ray_tpu.put`` (the PR 5 pull engine moves
        it to the decode pool zero-copy / multi-source striped), and
        return the ref + routing metadata.  ``done=True`` short-circuits
        requests that finished at their first token — no handoff ships.
        """
        import ray_tpu
        from ray_tpu.runtime.core_worker import get_global_worker

        t0 = time.monotonic()
        h = await self.engine.export_prefill(
            request["prompt"],
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"))
        ttft_s = time.monotonic() - t0
        self._observe_latency(ttft_s, ttft_s, 1)
        if h.finish_reason is not None:
            return {"done": True, "first_token": h.first_token,
                    "finish_reason": h.finish_reason,
                    "prompt_len": h.prompt_len,
                    "time_to_first_token_s": ttft_s}
        # optional int8 wire quantization (docs/serve_frontdoor.md):
        # encode BEFORE the store publish so both the put and the
        # cross-host pull move ~4x fewer bytes; the decode replica
        # restores the raw layout before import
        if CONFIG.serve_handoff_quantize and h.kv is not None:
            h = _encode_handoff(h)
            _M_HANDOFF_SAVED.inc(h.raw_nbytes - h.nbytes)
        t1 = time.monotonic()
        ref = ray_tpu.put(h)
        put_ms = (time.monotonic() - t1) * 1e3
        # handoff-export hop in the request's trace (the actor-call
        # execution span is the parent): gather+fetch+publish cost
        trh.instant_span("handoff_export", "handoff",
                         dur_ms=h.export_ms + put_ms,
                         bytes=h.nbytes, npages=h.npages)
        # the ref pin keeps the object alive (we own it) until the
        # decode pool pulled a copy; expired pins sweep FIFO (also from
        # autoscale_load so an idle replica doesn't retain its last
        # burst's KV objects forever)
        self._sweep_handoff_pins()
        self._handoff_pins.append(
            (time.monotonic() + self._handoff_pin_ttl_s, ref))
        _M_HANDOFF_BYTES.observe("export", h.nbytes)
        _M_HANDOFF_MS.observe("export_gather", h.export_ms)
        _M_HANDOFF_MS.observe("export_put", put_ms)
        _record_handoff_event("export", ref.id.hex(), h.nbytes,
                              h.export_ms + put_ms, npages=h.npages)
        return {"handoff": ref, "first_token": h.first_token,
                "prompt_len": h.prompt_len, "npages": h.npages,
                "nbytes": h.nbytes,
                "node": get_global_worker().node_id,
                "time_to_first_token_s": ttft_s}

    async def decode(self, handoff: Any, request: Dict[str, Any]):
        """Decode-pool entrypoint (async generator, reached via
        ``handle.decode.remote_streaming``): pull the handoff object off
        the transfer plane, admit it straight into a decode slot
        (page-table remap, no prefill), and stream each decoded token,
        then a summary dict.

        Pool-full admission is retried HERE first (in-process: an
        engine re-enqueue costs microseconds) for up to
        ``import_retry_s`` — under saturation most rejections are
        transient page pressure, and bouncing each one back through a
        fresh routed streaming call costs ~1000x more (the re-queue
        storm shows up directly as lost decode tokens/s on a shared
        host).  Only a PERSISTENTLY full pool escapes as
        KVPoolFullError for the router to re-queue elsewhere."""
        import ray_tpu
        from ray_tpu.exceptions import KVPoolFullError
        from ray_tpu.serve.llm_engine import GenerationResult, \
            PrefillHandoff

        pull_ms = 0.0
        if not isinstance(handoff, PrefillHandoff):
            # an ObjectRef: fetch via the pull engine (multi-source
            # striped, zero-copy landing), off the replica's event loop.
            # The handoff-pull hop span wraps the whole fetch; bind_ctx
            # carries the request's trace onto the executor thread so
            # the transfer engine's own pull span nests under it.
            sp_pull = trh.open_span("handoff_pull", "hop")
            t0 = time.monotonic()
            loop = asyncio.get_running_loop()
            ref = handoff
            handoff = await loop.run_in_executor(
                None, trh.bind_ctx(
                    sp_pull.ctx() if sp_pull is not None else None,
                    lambda: ray_tpu.get(ref, timeout=60.0)))
            pull_ms = (time.monotonic() - t0) * 1e3
            if sp_pull is not None:
                sp_pull.end(bytes=handoff.nbytes, npages=handoff.npages)
            _M_HANDOFF_BYTES.observe("import", handoff.nbytes)
            _M_HANDOFF_MS.observe("import_pull", pull_ms)
            _record_handoff_event("import", ref.id.hex(),
                                  handoff.nbytes, pull_ms,
                                  npages=handoff.npages)
        if getattr(handoff, "codec", None):
            # quantized wire handoff: restore the raw KV array (the
            # engine's import path scatters the pool layout verbatim)
            handoff = _decode_handoff(handoff)
        # import-wait hop: admission into a decode slot (page-table
        # remap, plus any pool-full backoff) — the "import wait" budget
        # line of a traced request
        sp_admit = trh.open_span("import_wait", "hop")
        deadline = time.monotonic() + self.import_retry_s
        backoff = 0.02
        while True:
            agen = self.engine.stream_import(handoff)
            try:
                first = await agen.__anext__()
                if sp_admit is not None:
                    sp_admit.end(npages=handoff.npages)
                break
            except KVPoolFullError:
                if time.monotonic() >= deadline:
                    if sp_admit is not None:
                        sp_admit.end(trh.ERROR,
                                     error_type="KVPoolFullError")
                    raise
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
            except StopAsyncIteration:
                if sp_admit is not None:
                    sp_admit.end()
                return
        # TPOT clock starts at admission, AFTER any pool-full wait:
        # queue time must not masquerade as inter-token latency
        start = time.monotonic()
        async for item in self._chain_first(first, agen):
            if isinstance(item, GenerationResult):
                # TTFT belongs to the prefill pool; decode owns TPOT
                if len(item.tokens) > 1:
                    _M_TPOT.observe(self.role,
                                    (time.monotonic() - start) * 1e3
                                    / (len(item.tokens) - 1))
                yield {
                    "finish_reason": item.finish_reason,
                    "num_tokens": len(item.tokens),
                    "prompt_len": handoff.prompt_len,
                    "handoff_pull_ms": round(pull_ms, 3),
                    "latency_s": item.latency_s,
                }
                return
            yield {"token": int(item)}

    async def stream(self, request: Dict[str, Any]):
        """Token-streaming entrypoint: an async generator yielding one
        ``{"token": id}`` dict per generated token as it is decoded,
        then a final summary dict.  Reached via
        ``handle.stream.remote_streaming(request)`` — the Serve handle
        submits the replica's streaming path with
        ``num_returns="streaming"``, so the caller's first item lands
        before decode finishes (time-to-first-token, not
        time-to-last)."""
        from ray_tpu.serve.llm_engine import GenerationResult
        async for item in self.engine.stream(
                request["prompt"],
                max_new_tokens=int(request.get("max_new_tokens", 32)),
                temperature=float(request.get("temperature", 0.0)),
                eos_id=request.get("eos_id")):
            if isinstance(item, GenerationResult):
                self._observe_latency(item.time_to_first_token_s,
                                      item.latency_s, len(item.tokens))
                yield {
                    "finish_reason": item.finish_reason,
                    "num_tokens": len(item.tokens),
                    "prompt_len": item.prompt_len,
                    "time_to_first_token_s": item.time_to_first_token_s,
                    "latency_s": item.latency_s,
                }
            else:
                yield {"token": int(item)}

    def stats(self) -> Dict[str, Any]:
        out = self.engine.stats.snapshot(self.engine.num_slots)
        out["role"] = self.role
        return out

    def advertised_prefixes(self) -> Optional[Dict[str, Any]]:
        """Resident prompt-prefix digests for the replica metrics path
        (docs/serve_frontdoor.md): the controller republishes these on
        get_targets so handles prefix-affinity-route the prefill hop.
        None (advertise nothing) when the engine's prefix cache is
        off."""
        if not getattr(self.engine, "prefix_cache_pages", 0):
            return None
        return {"page_size": self.engine.page_size,
                "digests": self.engine.prefix_digests()}

    def _sweep_handoff_pins(self) -> None:
        now = time.monotonic()
        while self._handoff_pins and self._handoff_pins[0][0] <= now:
            self._handoff_pins.popleft()

    def autoscale_load(self):
        """Per-pool scaling signal read by the replica's get_metrics ->
        controller (serve/controller.py _autoscale).  A decode pool
        scales off DECODE-SLOT PRESSURE (busy slots + admitted handoffs
        waiting for one) — its in-flight request count undercounts
        demand when streams are consumer-paced and overcounts when
        slots turn over faster than clients drain.  A prefill pool
        returns None: every in-flight ``prefill()`` call IS a queued-or-
        running engine prefill (it resolves the instant the handoff
        leaves the engine), so the replica's ongoing-request count
        already equals prefill-queue depth exactly.

        Doubles as the idle-time housekeeping hook (health checks call
        it every couple of seconds): expired handoff pins are swept
        here so a quiet prefill replica releases its last burst's KV
        objects."""
        self._sweep_handoff_pins()
        if self.role == "decode":
            ls = self.engine.load_snapshot()
            return float(ls["busy_slots"] + ls["ready"] + ls["imports"])
        return None


def build_app(preset: str = "tiny", *, num_replicas: int = 1,
              max_concurrent_queries: int = 64, num_tpus: float = 0,
              autoscaling_config: Optional[Dict[str, Any]] = None,
              disaggregated: bool = False,
              prefill_replicas: int = 1,
              prefill_autoscaling: Optional[Dict[str, Any]] = None,
              prefill_server_kwargs: Optional[Dict[str, Any]] = None,
              **server_kwargs):
    """Deployment-bound application for serve.run().

    ``num_tpus``: chips each replica leases.  MUST be > 0 to serve on
    TPU — a replica with no TPU lease is pinned to the CPU backend by
    the raylet (worker_main must not grab libtpu from under a training
    job; raylet._tpu_env), and a gpt-scale engine on one CPU core
    serves ~100x slower.  CI tests on CPU-only clusters keep 0.

    ``autoscaling_config``: queue-depth replica autoscaling (min/max
    replicas, target_num_ongoing_requests_per_replica, up/downscale
    delays — serve/config.py AutoscalingConfig).  Each LLM replica owns
    a full engine, so scaling 1->2 doubles both KV pool and chip
    demand; the BASELINE.md north-star pairs this with pod-slice
    autoscaling at the cluster layer.

    ``disaggregated=True`` materializes TWO pools instead of one
    (docs/serve_disagg.md): ``llm-<preset>-prefill`` (prefill_replicas,
    ``prefill_autoscaling``, ``prefill_server_kwargs`` overrides) and
    ``llm-<preset>-decode`` (``num_replicas`` / ``autoscaling_config``
    / ``server_kwargs``), each autoscaled independently off its own
    signal (LLMServer.autoscale_load).  Route through
    ``disagg_handle(preset)`` — the returned app's root is the decode
    pool, with the prefill pool deployed as its dependency."""
    if not disaggregated:
        dep = deployment(
            LLMServer, name=f"llm-{preset}", num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            autoscaling_config=autoscaling_config,
            ray_actor_options={"num_tpus": num_tpus} if num_tpus else None)
        return dep.bind(preset, **server_kwargs)
    actor_opts = {"num_tpus": num_tpus} if num_tpus else None
    pkw = dict(server_kwargs)
    pkw.update(prefill_server_kwargs or {})
    pkw.update(role="prefill", paged=True)
    dkw = dict(server_kwargs)
    dkw.update(role="decode", paged=True)
    prefill_dep = deployment(
        LLMServer, name=f"llm-{preset}-prefill",
        num_replicas=prefill_replicas,
        max_concurrent_queries=max_concurrent_queries,
        autoscaling_config=prefill_autoscaling,
        ray_actor_options=actor_opts)
    decode_dep = deployment(
        LLMServer, name=f"llm-{preset}-decode",
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        autoscaling_config=autoscaling_config,
        ray_actor_options=actor_opts)
    # the prefill app rides as a (ignored) init dependency so one
    # serve.run deploys both pools; run it WITHOUT a name override or
    # disagg_handle() won't find the canonical deployment names
    return decode_dep.bind(
        preset, _upstream=prefill_dep.bind(preset, **pkw), **dkw)


def disagg_handle(preset: str = "tiny"):
    """Client-side prefill->decode router for a ``disaggregated=True``
    app deployed by serve.run (serve/handle.py DisaggHandle): streams
    the first token as soon as the prefill pool samples it, then the
    decode pool's tokens; handles KV-pool-full re-queueing and replica-
    death mid-stream retries."""
    from ray_tpu.serve.handle import DisaggHandle
    return DisaggHandle(f"llm-{preset}-prefill", f"llm-{preset}-decode")
