"""Serve deployment for LLM generation on TPU replicas.

The north-star serving shape (BASELINE.md: "Serve llama-3-8b, TPU
replicas"): each replica owns one chip-resident LLMEngine
(serve/llm_engine.py, continuous batching over KV-cache slots) and an
async ``__call__`` that admits the request and awaits its completion —
concurrent Serve requests interleave at token granularity inside one
replica, and `num_replicas` scales across chips/hosts like any other
deployment.

Reference analog: `python/ray/serve` has no LLM-aware deployment; its
LLM benchmarks drive plain replicas.  This module is where the TPU
framework goes past parity.

Usage::

    from ray_tpu import serve
    app = serve.llm.build_app(preset="gpt-small", num_slots=8)
    handle = serve.run(app)
    out = ray_tpu.get(handle.remote({"prompt": [1, 2, 3],
                                     "max_new_tokens": 16}))
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.serve.deployment import deployment


class LLMServer:
    """Replica class: one engine per replica, admission via async call.

    ``checkpoint``: optional orbax/train checkpoint directory holding
    ``params``; absent means randomly initialized weights (shape-correct
    perf benchmarking without a weights file).
    """

    def __init__(self, preset: str = "tiny", *, num_slots: int = 8,
                 checkpoint: Optional[str] = None,
                 max_prompt_len: Optional[int] = None,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 block_size: int = 32, max_seq_len: Optional[int] = None,
                 warmup_prompt_lens: Optional[list] = None,
                 warmup_burst: int = 0,
                 paged: bool = False, page_size: int = 64,
                 kv_pool_pages: Optional[int] = None,
                 config_overrides: Optional[Dict[str, Any]] = None):
        from ray_tpu.models.configs import get_config
        from ray_tpu.serve.llm_engine import LLMEngine

        cfg = get_config(preset, **(config_overrides or {}))
        params = self._load_params(cfg, checkpoint, seed)
        self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                max_prompt_len=max_prompt_len,
                                top_k=top_k, top_p=top_p, seed=seed,
                                block_size=block_size,
                                max_seq_len=max_seq_len, paged=paged,
                                page_size=page_size,
                                kv_pool_pages=kv_pool_pages)
        if warmup_prompt_lens:
            # pay all compiles at replica start, none at request time
            # (warmup_burst additionally compiles the paged engine's
            # saturation-burst fetch shapes — see LLMEngine.warmup)
            self.engine.warmup(prompt_lens=warmup_prompt_lens,
                               burst=warmup_burst)

    @staticmethod
    def _load_params(cfg, checkpoint: Optional[str], seed: int):
        from ray_tpu.models.gpt import GPT
        if checkpoint:
            from ray_tpu.air.checkpoint import Checkpoint
            ckpt = Checkpoint.from_directory(checkpoint)
            state = ckpt.to_dict()
            for key in ("params", "model_params"):
                if key in state:
                    return state[key]
            raise ValueError(
                f"checkpoint at {checkpoint} has no 'params' entry "
                f"(keys: {sorted(state)})")
        model = GPT(cfg, decode=True)
        tokens = jnp.zeros((1, 1), jnp.int32)
        return model.init(jax.random.PRNGKey(seed), tokens)["params"]

    async def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        prompt = request["prompt"]
        result = await self.engine.submit(
            prompt,
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"))
        return {
            "tokens": result.tokens,
            "finish_reason": result.finish_reason,
            "prompt_len": result.prompt_len,
            "time_to_first_token_s": result.time_to_first_token_s,
            "latency_s": result.latency_s,
        }

    async def stream(self, request: Dict[str, Any]):
        """Token-streaming entrypoint: an async generator yielding one
        ``{"token": id}`` dict per generated token as it is decoded,
        then a final summary dict.  Reached via
        ``handle.stream.remote_streaming(request)`` — the Serve handle
        submits the replica's streaming path with
        ``num_returns="streaming"``, so the caller's first item lands
        before decode finishes (time-to-first-token, not
        time-to-last)."""
        from ray_tpu.serve.llm_engine import GenerationResult
        async for item in self.engine.stream(
                request["prompt"],
                max_new_tokens=int(request.get("max_new_tokens", 32)),
                temperature=float(request.get("temperature", 0.0)),
                eos_id=request.get("eos_id")):
            if isinstance(item, GenerationResult):
                yield {
                    "finish_reason": item.finish_reason,
                    "num_tokens": len(item.tokens),
                    "prompt_len": item.prompt_len,
                    "time_to_first_token_s": item.time_to_first_token_s,
                    "latency_s": item.latency_s,
                }
            else:
                yield {"token": int(item)}

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats.snapshot(self.engine.num_slots)


def build_app(preset: str = "tiny", *, num_replicas: int = 1,
              max_concurrent_queries: int = 64, num_tpus: float = 0,
              autoscaling_config: Optional[Dict[str, Any]] = None,
              **server_kwargs):
    """Deployment-bound application for serve.run().

    ``num_tpus``: chips each replica leases.  MUST be > 0 to serve on
    TPU — a replica with no TPU lease is pinned to the CPU backend by
    the raylet (worker_main must not grab libtpu from under a training
    job; raylet._tpu_env), and a gpt-scale engine on one CPU core
    serves ~100x slower.  CI tests on CPU-only clusters keep 0.

    ``autoscaling_config``: queue-depth replica autoscaling (min/max
    replicas, target_num_ongoing_requests_per_replica, up/downscale
    delays — serve/config.py AutoscalingConfig).  Each LLM replica owns
    a full engine, so scaling 1->2 doubles both KV pool and chip
    demand; the BASELINE.md north-star pairs this with pod-slice
    autoscaling at the cluster layer."""
    dep = deployment(
        LLMServer, name=f"llm-{preset}", num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        autoscaling_config=autoscaling_config,
        ray_actor_options={"num_tpus": num_tpus} if num_tpus else None)
    return dep.bind(preset, **server_kwargs)
