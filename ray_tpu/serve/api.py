"""serve.start/run/shutdown/delete/status — the public Serve API.

Analog of /root/reference/python/ray/serve/api.py (serve.run :455) and
_private/client.py: ``start`` launches the detached controller (+ HTTP
proxy), ``run`` deploys an Application graph bottom-up and returns a
handle to the root deployment.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve.controller import (CONTROLLER_NAME, SERVE_NAMESPACE,
                                      ServeController)
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle

PROXY_NAME = "SERVE_PROXY"


def _ensure_proxy(http_options: HTTPOptions) -> None:
    try:
        ray_tpu.get_actor(PROXY_NAME, namespace=SERVE_NAMESPACE)
        return  # already running (port changes need serve.shutdown first)
    except ValueError:
        pass
    from ray_tpu.serve.http_proxy import HTTPProxyActor
    proxy = ray_tpu.remote(HTTPProxyActor).options(
        name=PROXY_NAME, namespace=SERVE_NAMESPACE,
        lifetime="detached", max_concurrency=16, num_cpus=0.1,
    ).remote(http_options.host, http_options.port)
    ray_tpu.get(proxy.ready.remote(), timeout=30)


def _get_controller(create: bool = False,
                    http_options: Optional[HTTPOptions] = None):
    controller = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
    except ValueError:
        if not create:
            raise RuntimeError(
                "Serve is not running; call serve.start() or serve.run()")
    if controller is None:
        controller = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE,
            lifetime="detached", max_concurrency=16, num_cpus=0.1,
        ).remote()
        ray_tpu.get(controller.ping.remote(), timeout=30)
    if http_options is not None:
        _ensure_proxy(http_options)
    return controller


def start(http_options: Optional[HTTPOptions] = None, *,
          http: bool = False) -> None:
    """Start the Serve instance (controller + optional HTTP proxy)."""
    if http and http_options is None:
        http_options = HTTPOptions()
    _get_controller(create=True, http_options=http_options)


def run(target: Application, *, name: Optional[str] = None,
        _blocking_until_healthy: bool = True,
        http_options: Optional[HTTPOptions] = None) -> DeploymentHandle:
    """Deploy an application graph; returns a handle to the root deployment.

    Bound sub-applications (``Deployment.bind`` args) deploy first and are
    replaced with DeploymentHandles in the parent's init args — the
    deployment-graph build of reference
    serve/_private/deployment_graph_build.py.
    """
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError("serve.run expects a bound Application "
                        "(use Deployment.bind(...))")
    controller = _get_controller(create=True, http_options=http_options)

    apps = target._flatten()
    for app in apps:
        dep = app.deployment
        dep_name = (name if app is target and name else dep.name)

        def materialize(v):
            if isinstance(v, Application):
                return DeploymentHandle(v.deployment.name)
            return v

        init_args = tuple(materialize(a) for a in app.init_args)
        init_kwargs = {k: materialize(v)
                       for k, v in app.init_kwargs.items()}
        serialized = cloudpickle.dumps(
            (dep.func_or_class, init_args, init_kwargs))
        ray_tpu.get(controller.deploy.remote(
            dep_name, serialized, dep.config.to_dict()), timeout=30)

    root_name = name or target.deployment.name
    deployed = {(name if app is target and name else app.deployment.name)
                for app in apps}
    if _blocking_until_healthy:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            st = ray_tpu.get(controller.status.remote(), timeout=10)
            if all(s["status"] == "HEALTHY"
                   for n, s in st.items() if n in deployed):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(
                "deployments not healthy: "
                f"{ {n: s for n, s in st.items() if n in deployed} }")
    return DeploymentHandle(root_name)


def get_app_handle(name: str) -> DeploymentHandle:
    controller = _get_controller()
    if name not in ray_tpu.get(controller.list_deployments.remote()):
        raise ValueError(f"no deployment named {name!r}")
    return DeploymentHandle(name)


get_deployment_handle = get_app_handle


def status() -> Dict[str, Any]:
    controller = _get_controller()
    return ray_tpu.get(controller.status.remote(), timeout=10)


def delete(name: str) -> None:
    controller = _get_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=30)


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown_serve.remote(), timeout=30)
    except Exception:
        pass
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME, namespace=SERVE_NAMESPACE)
        ray_tpu.kill(proxy)
    except Exception:
        pass
    ray_tpu.kill(controller)
