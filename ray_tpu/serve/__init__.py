"""Serve: scalable model serving on ray_tpu actors.

TPU-native analog of the reference Serve library
(/root/reference/python/ray/serve): a detached ServeController actor owns
deployment state and reconciles replica actors; handles route requests with
power-of-two-choices load balancing; an aiohttp HTTP proxy fronts
deployments; autoscaling reacts to per-replica queue metrics.

Adapted to the TPU process model: replicas that hold TPU chips get
``num_tpus`` resources so one replica owns the host's chips, and the router
keeps TPU replicas saturated with in-flight batches (continuous batching via
``@serve.batch``).
"""

from ray_tpu.serve.api import (delete, get_app_handle, get_deployment_handle,
                               run, shutdown, start, status)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, HTTPOptions
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle


def __getattr__(name):
    # serve.llm pulls jax (the engine); load it only when asked for so
    # plain serve users keep the fast no-jax import
    if name == "llm":
        import importlib
        return importlib.import_module("ray_tpu.serve.llm")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "start", "run", "shutdown", "delete", "status", "deployment",
    "Deployment", "Application", "DeploymentHandle", "batch",
    "AutoscalingConfig", "HTTPOptions", "get_app_handle",
    "get_deployment_handle",
]
