"""Serve REST schema: declarative application/deployment descriptions.

Analog of /root/reference/python/ray/serve/schema.py (ServeApplicationSchema,
DeploymentSchema, ServeStatusSchema — pydantic there, stdlib dataclasses
here since the image pins no pydantic).  The same dicts flow through the
dashboard REST endpoints (`/api/serve/applications`) and the `ray serve`
CLI, and `apply()` builds/updates a running application from the declarative
form (reference serve deploy semantics: import_path + per-deployment
overrides).
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    user_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class ServeApplicationSchema:
    """One application: an import path to a bound Application + overrides."""

    import_path: str = ""
    name: str = "default"
    route_prefix: Optional[str] = "/"
    runtime_env: Optional[Dict[str, Any]] = None
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"import_path": self.import_path,
                               "name": self.name,
                               "route_prefix": self.route_prefix}
        if self.runtime_env:
            out["runtime_env"] = self.runtime_env
        if self.deployments:
            out["deployments"] = [d.to_dict() for d in self.deployments]
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        deployments = [DeploymentSchema.from_dict(x)
                       for x in d.get("deployments", [])]
        return cls(import_path=d.get("import_path", ""),
                   name=d.get("name", "default"),
                   route_prefix=d.get("route_prefix", "/"),
                   runtime_env=d.get("runtime_env"),
                   deployments=deployments)

    # ------------------------------------------------------------ execution
    def load_application(self):
        """Import the bound Application named by ``import_path``
        ("module.sub:app" or "module.sub.app")."""
        path = self.import_path
        if ":" in path:
            mod_name, attr = path.split(":", 1)
        else:
            mod_name, _, attr = path.rpartition(".")
        if not mod_name or not attr:
            raise ValueError(f"bad import path {path!r}")
        app = getattr(importlib.import_module(mod_name), attr)
        from ray_tpu.serve.deployment import Application
        if not isinstance(app, Application):
            raise TypeError(f"{path} is {type(app).__name__}, expected a "
                            "bound Application (deployment.bind(...))")
        return app

    def apply(self):
        """serve.run the imported application with this schema's overrides
        (reference `serve deploy` path)."""
        from ray_tpu import serve
        if self.runtime_env or (self.route_prefix not in (None, "/")):
            from ray_tpu._private.logging_utils import get_logger
            get_logger("serve").warning(
                "ServeApplicationSchema: runtime_env/route_prefix are "
                "accepted for config compatibility but not applied yet "
                "(HTTP routing is deployment-name based)")
        app = self.load_application()
        overrides = {d.name: d for d in self.deployments}
        for node in app._flatten():
            ov = overrides.get(node.deployment.name)
            if ov is None:
                continue
            opts: Dict[str, Any] = {}
            if ov.num_replicas is not None:
                opts["num_replicas"] = ov.num_replicas
            if ov.max_concurrent_queries is not None:
                opts["max_concurrent_queries"] = ov.max_concurrent_queries
            if ov.user_config is not None:
                opts["user_config"] = ov.user_config
            if ov.autoscaling_config is not None:
                opts["autoscaling_config"] = ov.autoscaling_config
            if ov.ray_actor_options is not None:
                opts["ray_actor_options"] = ov.ray_actor_options
            if opts:
                node.deployment = node.deployment.options(**opts)
        return serve.run(app, name=None if self.name == "default"
                         else self.name)


def serve_status_schema() -> Dict[str, Any]:
    """Cluster-wide serve status dict (ServeStatusSchema analog)."""
    from ray_tpu import serve
    try:
        return serve.status()
    except Exception as e:  # controller not running
        return {"applications": {}, "error": str(e)}
