"""Serve configuration dataclasses.

Analog of /root/reference/python/ray/serve/config.py (DeploymentConfig,
AutoscalingConfig, HTTPOptions) — plain dataclasses instead of pydantic
(pydantic isn't a baked-in dependency).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu._private.config import CONFIG


@dataclass
class AutoscalingConfig:
    """Queue-depth autoscaling policy knobs.

    Cf. reference serve/config.py AutoscalingConfig and
    _private/autoscaling_policy.py: target ongoing requests per replica
    drives desired replica count, with hysteresis delays.
    """
    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0
    metrics_interval_s: float = 0.5


@dataclass
class HTTPOptions:
    # defaults resolve from the central flag table at construction so
    # RAY_TPU_SERVE_HTTP_HOST/PORT env overrides reach `serve.start()`
    # callers that never build an explicit HTTPOptions
    host: str = field(default_factory=lambda: CONFIG.serve_http_host)
    port: int = field(default_factory=lambda: CONFIG.serve_http_port)


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    # replicas loading big models (LLM weights + first TPU compile) need a
    # long startup window before health checks can kill them
    health_check_grace_period_s: float = 120.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self.__dict__)
        if self.autoscaling_config is not None:
            d["autoscaling_config"] = dict(self.autoscaling_config.__dict__)
        return d
