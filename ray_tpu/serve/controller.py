"""ServeController: detached actor owning all deployment state.

Analog of /root/reference/python/ray/serve/controller.py (ServeController
:61) + _private/deployment_state.py (DeploymentState/DeploymentStateManager
:958/:1767): a reconcile loop drives each deployment's replica set toward
its target (rolling updates via version stamps, health checks, autoscaling
from replica queue metrics).

Config propagation: the reference pushes via LongPollHost
(_private/long_poll.py:185). ray_tpu actors execute methods from one
ordered queue, so a blocking long-poll would starve the controller;
handles/proxies instead short-poll ``get_targets`` with a version stamp
(cheap dict compare server-side) — same eventual-consistency contract.
"""

from __future__ import annotations

import math
import threading
import time
import uuid
from typing import Any, Dict, Optional

from ray_tpu._private.config import CONFIG

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"
REPLICA_PREFIX = "SERVE_REPLICA::"


class ServeController:
    def __init__(self):
        # deployment name -> state dict
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._global_version = 0
        self._shutdown = False
        # SLO-driven elastic re-roling (docs/serve_frontdoor.md): at
        # most ONE replica moves between a disagg pair's pools at a
        # time; the pending move + the last per-route violation
        # snapshot live here
        self._rerole: Optional[Dict[str, Any]] = None
        self._last_rerole_done = 0.0
        self._last_rerole_check = 0.0
        self._slo_last: Dict[str, tuple] = {}
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True)
        self._reconcile_thread.start()

    # ----------------------------------------------------------- public API
    def deploy(self, name: str, serialized_init: bytes,
               config: Dict[str, Any]) -> None:
        with self._lock:
            state = self._deployments.get(name)
            version = (state["version"] + 1) if state else 1
            auto = config.get("autoscaling_config")
            target = config.get("num_replicas", 1)
            if auto:
                target = max(auto["min_replicas"],
                             min(target, auto["max_replicas"]))
            self._deployments[name] = {
                "name": name,
                "version": version,
                # routing_version bumps on ANY replica-set change (scale,
                # crash retirement, rolling update) so handles always see
                # fresh tables; "version" stamps the code/config rollout.
                "routing_version": (state["routing_version"] + 1) if state
                                   else 1,
                "serialized_init": serialized_init,
                "config": config,
                "target_replicas": target,
                "replicas": dict(state["replicas"]) if state else {},
                # replica_tag -> {"name", "version", "healthy"}
                "status": "UPDATING",
                "last_scale_up": 0.0,
                "last_scale_down": 0.0,
                "ongoing_history": [],
            }
            self._global_version += 1

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            state = self._deployments.pop(name, None)
            self._global_version += 1
        if state:
            for info in state["replicas"].values():
                self._kill_replica(info["name"])

    def get_targets(self, name: str,
                    known_version: int = -1) -> Optional[Dict[str, Any]]:
        """Replica routing table for one deployment; handles poll this.

        ``loads`` (replica name -> last health-checked load signal) and
        ``nodes`` (replica name -> node id) ride EVERY reply, including
        version-unchanged ones: loads cover traffic other handles sent
        (handle-local in-flight counts can't see it — power-of-two
        choices on stale or handle-local-only depth hotspots a decode
        pool under skewed stream lengths), and they change every
        health-check pass without bumping routing_version."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return None
            loads = {i["name"]: i.get("last_load", 0.0)
                     for i in state["replicas"].values()
                     if i["healthy"] and not i.get("draining")}
            # prefix-digest advertisements ride every reply like loads
            # (docs/serve_frontdoor.md): they change each health-check
            # pass without bumping routing_version, and handles rebuild
            # their affinity index from the full current set.  Absent
            # entirely (not empty) when no replica advertises, so
            # non-LLM handles never materialize an index.
            prefixes = {i["name"]: i["last_prefixes"]
                        for i in state["replicas"].values()
                        if i["healthy"] and not i.get("draining")
                        and i.get("last_prefixes")}
            if state["routing_version"] == known_version:
                out = {"version": known_version, "unchanged": True,
                       "loads": loads}
            else:
                out = {
                    "version": state["routing_version"],
                    "replicas": [i["name"]
                                 for i in state["replicas"].values()
                                 if i["healthy"] and not i.get("draining")
                                 and i["version"] == state["version"]],
                    "nodes": {i["name"]: i.get("node_id", "")
                              for i in state["replicas"].values()},
                    "loads": loads,
                    "max_concurrent_queries":
                        state["config"].get("max_concurrent_queries", 8),
                }
            if prefixes:
                out["prefixes"] = prefixes
            return out

    def list_deployments(self):
        with self._lock:
            return sorted(self._deployments)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "status": s["status"],
                    "version": s["version"],
                    "target_replicas": s["target_replicas"],
                    "running_replicas": sum(
                        1 for i in s["replicas"].values()
                        if i["healthy"] and not i.get("draining")
                        and i["version"] == s["version"]),
                    "replicas": [tag for tag, i in s["replicas"].items()
                                 if i["healthy"] and not i.get("draining")
                                 and i["version"] == s["version"]],
                }
                for name, s in self._deployments.items()
            }

    def shutdown_serve(self) -> None:
        with self._lock:
            self._shutdown = True
            deployments = list(self._deployments.values())
            self._deployments.clear()
        try:
            from ray_tpu.experimental import internal_kv
            internal_kv._internal_kv_del("serve:status")
        except Exception:
            pass  # dashboard may briefly show stale status
        for state in deployments:
            for info in state["replicas"].values():
                self._kill_replica(info["name"])

    def ping(self) -> bool:
        return True

    # ------------------------------------------------------------ re-roling
    def request_rerole(self, src: str, dst: str, *,
                       reason: str = "manual",
                       slo_kind: Optional[str] = None,
                       trace_id: Optional[str] = None) -> bool:
        """Move one replica's worth of capacity from deployment ``src``
        to ``dst`` (docs/serve_frontdoor.md re-roling control loop):
        the lowest-load ``src`` replica starts draining immediately
        (it leaves the routing table this instant, finishes its
        in-flight streams, then retires), ``dst``'s target rises by
        one, and the reconcile loop converges both pools.  Emits
        SERVE_REROLE now and SERVE_REROLE_DONE when both pools reach
        their new targets — the pair the recovery auditor folds into a
        ``rerole`` episode (kind, ``recovery_slo_rerole_s``).

        Refused (returns False) while another re-role is in flight, for
        unknown deployments, or when ``src`` cannot give up a replica
        without emptying (its target must stay >= 1)."""
        from ray_tpu._private import cluster_events as cev

        with self._lock:
            s = self._deployments.get(src)
            d = self._deployments.get(dst)
            if s is None or d is None or self._rerole is not None:
                return False
            if s["target_replicas"] < 2:
                return False
            # the donor: lowest-load healthy replica — the cheapest
            # drain, and under prefix-affinity skew also the one whose
            # resident pages the router will miss least
            cand = [(i.get("last_load", 0.0), tag)
                    for tag, i in s["replicas"].items()
                    if i["healthy"] and not i.get("draining")]
            if not cand:
                return False
            donor = min(cand)[1]
            s["target_replicas"] -= 1
            d["target_replicas"] += 1
            # drain the chosen donor NOW (reconcile sees excess 0 and
            # drains nothing else); routing_version bumps so handles
            # polling "unchanged" drop it from their tables
            s["replicas"][donor]["draining"] = time.monotonic()
            s["routing_version"] += 1
            self._rerole = {
                "src": src, "dst": dst, "replica": donor,
                "src_target": s["target_replicas"],
                "dst_target": d["target_replicas"],
                "started": time.monotonic(),
            }
        cev.emit(cev.SERVE_REROLE,
                 f"re-roling one replica {src} -> {dst}: drain {donor} "
                 f"({reason})",
                 src=src, dst=dst, replica=donor, reason=reason,
                 slo_kind=slo_kind, trace_id=trace_id)
        return True

    def _check_rerole_done(self) -> None:
        """Close the pending re-role once both pools converged: the
        donor retired from ``src`` and ``dst`` runs at its raised
        target.  Emits SERVE_REROLE_DONE (the auditor's episode
        close)."""
        r = self._rerole
        if r is None:
            return
        with self._lock:
            s = self._deployments.get(r["src"])
            d = self._deployments.get(r["dst"])
            if s is None or d is None:
                # a redeploy/teardown raced the move: abandon it (the
                # episode stays open in the auditor, which is the
                # truthful record — convergence never happened)
                self._rerole = None
                return

            def _running(st):
                return sum(1 for i in st["replicas"].values()
                           if i["healthy"] and not i.get("draining")
                           and i["version"] == st["version"])

            src_n, dst_n = _running(s), _running(d)
            done = (dst_n >= r["dst_target"]
                    and r["replica"] not in s["replicas"])
        if not done:
            return
        from ray_tpu._private import cluster_events as cev
        cev.emit(cev.SERVE_REROLE_DONE,
                 f"re-role {r['src']} -> {r['dst']} complete: "
                 f"{src_n} / {dst_n} replicas",
                 src=r["src"], dst=r["dst"], replica=r["replica"],
                 src_replicas=src_n, dst_replicas=dst_n)
        self._rerole = None
        self._last_rerole_done = time.monotonic()

    def _maybe_rerole(self) -> None:
        """The SLO policy half of re-roling: every
        ``serve_rerole_interval_s`` read the ingress SLO route index
        (tracing_helper GcsSpanTable ``slo_by_route``) and, for each
        disagg pool pair, compare the interval's NEW ttft vs tpot
        violations on the pair's route.  TTFT burning -> the prefill
        pool is starved -> decode donates a replica; TPOT burning ->
        decode is starved -> prefill donates.  A tie or a trickle
        (under ``serve_rerole_min_violations``) moves nothing, and
        ``serve_rerole_cooldown_s`` spaces moves so a pool settles
        (drain + engine warmup) before the next reading acts."""
        if not CONFIG.serve_rerole_enabled or self._rerole is not None:
            return
        now = time.monotonic()
        if now - self._last_rerole_check < CONFIG.serve_rerole_interval_s:
            return
        self._last_rerole_check = now
        with self._lock:
            names = set(self._deployments)
        pairs = [n[:-len("-decode")] for n in names
                 if n.endswith("-decode")
                 and n[:-len("-decode")] + "-prefill" in names]
        if not pairs:
            return
        try:
            from ray_tpu.experimental.state.api import trace_stats
            slo = trace_stats().get("slo_by_route") or {}
        except Exception:
            return      # span table unreachable: no signal, no move
        in_cooldown = now - self._last_rerole_done \
            < CONFIG.serve_rerole_cooldown_s
        for base in pairs:
            decode, prefill = base + "-decode", base + "-prefill"
            slot = slo.get(decode)
            if not slot:
                continue
            cur = (int(slot.get("ttft_violation", 0)),
                   int(slot.get("tpot_violation", 0)))
            prev = self._slo_last.get(decode, (0, 0))
            # the snapshot always advances: violations burned during a
            # cooldown are consumed, not banked for a later move
            self._slo_last[decode] = cur
            if in_cooldown:
                continue
            d_ttft, d_tpot = cur[0] - prev[0], cur[1] - prev[1]
            if max(d_ttft, d_tpot) < CONFIG.serve_rerole_min_violations \
                    or d_ttft == d_tpot:
                continue
            exemplars = slot.get("exemplars") or []
            trace_id = exemplars[0].get("trace_id") if exemplars else None
            if d_ttft > d_tpot:
                self.request_rerole(
                    decode, prefill,
                    reason=f"{d_ttft} ttft violations on {decode} "
                           f"this interval (tpot: {d_tpot})",
                    slo_kind="ttft", trace_id=trace_id)
            else:
                self.request_rerole(
                    prefill, decode,
                    reason=f"{d_tpot} tpot violations on {decode} "
                           f"this interval (ttft: {d_ttft})",
                    slo_kind="tpot", trace_id=trace_id)
            return      # one move per reading across all pairs

    # ------------------------------------------------------- reconciliation
    def _reconcile_loop(self):
        while not self._shutdown:
            try:
                self._maybe_rerole()
                self._reconcile_once()
                self._check_rerole_done()
                self._publish_status()
            except Exception:  # noqa: BLE001 - loop must survive
                import traceback
                traceback.print_exc()
            time.sleep(max(0.01, CONFIG.serve_controller_loop_ms / 1000.0))

    def _publish_status(self):
        """Snapshot status into GCS internal KV so non-driver processes
        (dashboard REST, `ray serve status`) can read it — the role of the
        reference controller's GCS-KV checkpoints (serve controller.py:61
        'owns state in GCS KV')."""
        import json

        from ray_tpu.experimental import internal_kv
        snap = json.dumps(self.status(), sort_keys=True)
        if snap != getattr(self, "_last_status_snap", None):
            # put first: a failed put must retry next pass, not wait for
            # the next status transition
            internal_kv._internal_kv_put("serve:status", snap.encode())
            self._last_status_snap = snap

    def _reconcile_once(self):
        import ray_tpu
        from ray_tpu.serve.replica import ReplicaActor

        with self._lock:
            deployments = list(self._deployments.keys())
        for name in deployments:
            with self._lock:
                state = self._deployments.get(name)
                if state is None:
                    continue
                version = state["version"]
                target = state["target_replicas"]
                config = state["config"]
                replicas = dict(state["replicas"])

            # health checks + metrics; a replica is dead only after
            # HEALTH_CHECK_FAILURE_THRESHOLD consecutive failures (cf.
            # reference deployment_state ReplicaState STARTING vs RUNNING:
            # freshly created replicas get a startup grace period)
            healthy_current = []
            total_ongoing = 0.0
            metrics_partial = False
            for tag, info in list(replicas.items()):
                try:
                    handle = ray_tpu.get_actor(info["name"],
                                               namespace=SERVE_NAMESPACE)
                    metrics = ray_tpu.get(handle.get_metrics.remote(),
                                          timeout=config.get(
                                              "health_check_period_s", 2.0))
                    info["healthy"] = True
                    info["fails"] = 0
                    info["ever_healthy"] = True
                    info["last_ongoing"] = metrics["num_ongoing"]
                    # the autoscaling signal: the replica's custom load
                    # (per-pool queue depth / slot pressure) when its
                    # callable publishes one, else == num_ongoing
                    info["last_load"] = metrics.get(
                        "load", metrics["num_ongoing"])
                    info["last_prefixes"] = metrics.get("prefixes")
                    if metrics.get("node_id"):
                        info["node_id"] = metrics["node_id"]
                    total_ongoing += metrics["num_ongoing"]
                except Exception:
                    metrics_partial = True
                    info.pop("last_ongoing", None)
                    info.pop("last_load", None)
                    info.pop("last_prefixes", None)
                    info["fails"] = info.get("fails", 0) + 1
                    grace_s = config.get("health_check_grace_period_s", 120.0)
                    grace = (time.monotonic() - info.get("created_at", 0.0)
                             < grace_s)
                    # the startup grace shields a replica still LOADING
                    # (big model + first compile) from being shot before
                    # it ever answered; a replica that already served a
                    # health check and then went dark is DEAD — keeping
                    # it routable for the rest of the grace window would
                    # bounce every p2c pick that lands on it
                    if info["fails"] >= 3 and (info.get("ever_healthy")
                                               or not grace):
                        info["healthy"] = False
                if info["healthy"] and info["version"] == version:
                    healthy_current.append(tag)

            # autoscaling decision — when any replica's metrics read
            # failed this pass, the partial total_ongoing is a LOWER
            # bound on demand: upscaling on it is safe (e.g. a new
            # replica still compiling must not freeze a burst response),
            # but a phantom downscale would kill real work — suppressed.
            # The policy sees ONLY non-draining replicas (count and
            # ongoing): draining replicas take no new traffic, so their
            # near-zero ongoing would dilute the per-replica average and
            # suppress a needed upscale, while their finishing tails
            # would inflate demand and flap a scale-down back up.
            auto = config.get("autoscaling_config")
            if auto and healthy_current:
                serving = [t for t in healthy_current
                           if not replicas[t].get("draining")]
                # the policy consumes each replica's LOAD signal (custom
                # per-pool metric when published, == ongoing otherwise);
                # the non-draining denominator contract is unchanged and
                # holds per pool — each deployment reconciles alone
                serving_ongoing = sum(
                    replicas[t].get("last_load", 0.0) for t in serving)
                new_target = self._autoscale(name, auto, serving_ongoing,
                                             len(serving), target)
                if new_target > target or not metrics_partial:
                    if new_target != target:
                        # autoscale decision: one event per target
                        # change, with the inputs that drove it
                        # (docs/observability.md)
                        from ray_tpu._private import cluster_events \
                            as cev
                        cev.emit(
                            cev.AUTOSCALE,
                            f"deployment {name!r}: target "
                            f"{target} -> {new_target} "
                            f"(load={serving_ongoing:.1f} over "
                            f"{len(serving)} serving)",
                            deployment=name, old_target=target,
                            new_target=new_target,
                            load=round(serving_ongoing, 2))
                    target = new_target

            # a rising target revives draining replicas before spawning
            # new ones (their engine/caches are warm)
            active = [t for t in healthy_current
                      if not replicas[t].get("draining")]
            for tag in healthy_current:
                if len(active) >= target:
                    break
                if replicas[tag].get("draining"):
                    replicas[tag].pop("draining", None)
                    active.append(tag)

            # scale up: start missing replicas at the current version
            missing = target - len(active)
            for _ in range(max(0, missing)):
                tag = f"{name}#{uuid.uuid4().hex[:8]}"
                actor_name = REPLICA_PREFIX + tag
                opts = dict(config.get("ray_actor_options") or {})
                max_cq = config.get("max_concurrent_queries", 8)
                try:
                    ray_tpu.remote(ReplicaActor).options(
                        name=actor_name,
                        namespace=SERVE_NAMESPACE,
                        lifetime="detached",
                        max_concurrency=max_cq + 2,
                        num_cpus=opts.get("num_cpus", 0.1),
                        num_tpus=opts.get("num_tpus", 0.0),
                        resources=opts.get("resources"),
                    ).remote(state["serialized_init"], name, tag,
                             config.get("user_config"), max_cq)
                    replicas[tag] = {"name": actor_name, "version": version,
                                     "healthy": True, "fails": 0,
                                     "created_at": time.monotonic()}
                except Exception:
                    import traceback
                    traceback.print_exc()

            # scale down / retire old-version or unhealthy replicas.
            # Healthy excess replicas DRAIN instead of dying mid-request:
            # a draining replica leaves the routing table immediately
            # (get_targets filters on "draining") but is killed only once
            # its ongoing count hits zero or the drain grace expires —
            # cf. reference deployment_state graceful_shutdown_wait_loop_s.
            to_kill = []
            excess = len(active) - target
            drain_grace = config.get("graceful_shutdown_timeout_s", 30.0)
            now = time.monotonic()
            for tag, info in list(replicas.items()):
                if info["version"] != version or not info["healthy"]:
                    to_kill.append(tag)       # broken: no point draining
                elif excess > 0 and not info.get("draining"):
                    info["draining"] = now
                    excess -= 1
            # handles refresh their routing table at most every
            # _REFRESH_INTERVAL_S (1.0 s): a drained-empty replica must
            # outlive that window or a stale-table handle can land a
            # request in the instant between the idle check and the kill
            min_drain_s = 2.0
            for tag, info in list(replicas.items()):
                if tag in to_kill or not info.get("draining"):
                    continue
                # last_ongoing was fetched by the health loop THIS pass;
                # a failed read means unreachable != idle — keep
                # draining until the grace expires rather than shooting
                # a busy replica mid-request
                ongoing = info.get("last_ongoing")
                age = now - info["draining"]
                if (ongoing == 0 and age > min_drain_s) \
                        or age > drain_grace:
                    to_kill.append(tag)
            for tag in to_kill:
                info = replicas.pop(tag)
                from ray_tpu._private import cluster_events as cev
                why = ("unhealthy" if not info.get("healthy")
                       else "old version"
                       if info.get("version") != version
                       else "scaled down (drained)")
                cev.emit(cev.REPLICA_RETIRED,
                         f"deployment {name!r} replica {tag}: {why}",
                         severity="WARNING" if why == "unhealthy"
                         else "INFO",
                         deployment=name, replica=tag, reason=why)
                self._kill_replica(info["name"])

            with self._lock:
                cur = self._deployments.get(name)
                if cur is None:
                    # deployment deleted mid-pass: kill replicas we created
                    orphans = [i["name"] for i in replicas.values()]
                elif cur["version"] != version:
                    # deploy() raced us: keep every replica tracked so the
                    # next pass retires old-version ones (nothing orphaned)
                    orphans = []
                    for tag, info in replicas.items():
                        cur["replicas"].setdefault(tag, info)
                    cur["routing_version"] += 1
                else:
                    orphans = []
                    if (set(replicas) != set(cur["replicas"])
                            or any((replicas[t]["healthy"],
                                    bool(replicas[t].get("draining")))
                                   != (cur["replicas"][t]["healthy"],
                                       bool(cur["replicas"][t]
                                            .get("draining")))
                                   for t in replicas
                                   if t in cur["replicas"])):
                        cur["routing_version"] += 1
                    cur["replicas"] = replicas
                    cur["target_replicas"] = target
                    running = sum(1 for i in replicas.values()
                                  if i["healthy"] and not i.get("draining")
                                  and i["version"] == version)
                    cur["status"] = ("HEALTHY" if running >= target
                                     else "UPDATING")
            for actor_name in orphans:
                self._kill_replica(actor_name)

    def _autoscale(self, name: str, auto: Dict[str, Any], total_ongoing:
                   float, num_replicas: int, target: int) -> int:
        """Queue-depth policy, cf. reference
        serve/_private/autoscaling_policy.py (calculate_desired_num_replicas):
        ``desired = num_replicas * (avg_ongoing / target_per_replica)``.

        ``num_replicas`` and ``total_ongoing`` MUST cover the same set —
        the NON-draining replicas (the caller filters) — or the average
        is diluted/inflated by replicas that take no new traffic.
        """
        desired = math.ceil(
            total_ongoing /
            max(auto["target_num_ongoing_requests_per_replica"], 1e-6))
        desired = max(auto["min_replicas"],
                      min(auto["max_replicas"], desired))
        now = time.monotonic()
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return target
            if desired > target:
                if state["last_scale_up"] == 0.0:
                    state["last_scale_up"] = now
                if now - state["last_scale_up"] >= auto["upscale_delay_s"]:
                    state["last_scale_up"] = 0.0
                    state["last_scale_down"] = 0.0
                    return desired
            elif desired < target:
                if state["last_scale_down"] == 0.0:
                    state["last_scale_down"] = now
                if now - state["last_scale_down"] >= auto["downscale_delay_s"]:
                    state["last_scale_up"] = 0.0
                    state["last_scale_down"] = 0.0
                    return desired
            else:
                state["last_scale_up"] = 0.0
                state["last_scale_down"] = 0.0
        return target

    def _kill_replica(self, actor_name: str) -> None:
        import ray_tpu
        try:
            handle = ray_tpu.get_actor(actor_name,
                                       namespace=SERVE_NAMESPACE)
            try:
                ray_tpu.get(handle.prepare_for_shutdown.remote(), timeout=6)
            except Exception:
                pass
            ray_tpu.kill(handle)
        except Exception:
            pass
