"""@serve.batch — coalesce concurrent calls into one batched invocation.

Analog of /root/reference/python/ray/serve/batching.py (_BatchQueue).
Replicas are async actors, so concurrent handle_request coroutines each
submit one input and await a per-call future; a batcher thread drains the
queue into calls of the wrapped function with a list of inputs. Plain
threads (threaded actors, driver-side use) block on an event instead.

On TPU replicas this is the continuous-batching seam: the wrapped function
sees a padded batch it can feed to a jitted forward step.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import threading
import time
from typing import Any, Callable, List, Optional

from ray_tpu._private import runtime_metrics as rtm

_M_BATCH = rtm.histogram(
    "ray_tpu_serve_batch_size", "@serve.batch coalesced batch sizes",
    boundaries=rtm.COUNT_BOUNDARIES)

_QUEUE_CREATE_LOCK = threading.Lock()
_QUEUES: dict = {}


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._lock = threading.Condition()
        self._items: List[tuple] = []  # (instance, arg, deliver)
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def submit(self, instance, arg) -> Any:
        """From a plain thread: blocks until the batch result arrives.
        From inside an event loop (async replica / async actor): returns an
        awaitable instead — blocking would starve the very loop whose
        concurrent calls form the batch (the reason the reference's
        _BatchQueue is asyncio-native)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            fut = loop.create_future()

            def deliver(ok: bool, value: Any, _loop=loop, _fut=fut):
                if ok:
                    _loop.call_soon_threadsafe(
                        lambda: None if _fut.done()
                        else _fut.set_result(value))
                else:
                    _loop.call_soon_threadsafe(
                        lambda: None if _fut.done()
                        else _fut.set_exception(value))

            self._enqueue(instance, arg, deliver)
            return fut
        ev = threading.Event()
        out: dict = {}

        def deliver(ok: bool, value: Any):
            out["ok" if ok else "err"] = value
            ev.set()

        self._enqueue(instance, arg, deliver)
        ev.wait()
        if "err" in out:
            raise out["err"]
        return out["ok"]

    def _enqueue(self, instance, arg, deliver) -> None:
        with self._lock:
            self._items.append((instance, arg, deliver))
            self._ensure_thread()
            self._lock.notify()

    def _loop(self):
        while True:
            with self._lock:
                while not self._items:
                    self._lock.wait()
                # wait up to batch_wait_timeout_s for a full batch
                deadline = time.monotonic() + self._wait
                while (len(self._items) < self._max
                       and time.monotonic() < deadline):
                    self._lock.wait(timeout=deadline - time.monotonic())
                batch = self._items[:self._max]
                del self._items[:len(batch)]
            _M_BATCH.observe(len(batch))
            instance = batch[0][0]
            args = [b[1] for b in batch]
            try:
                if instance is not None:
                    results = self._fn(instance, args)
                else:
                    results = self._fn(args)
                if inspect.iscoroutine(results):
                    # reference @serve.batch functions are `async def`;
                    # drive the coroutine to completion on this loop thread
                    results = asyncio.run(results)
                if len(results) != len(args):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for a batch of {len(args)}")
                for (_, _, deliver), r in zip(batch, results):
                    deliver(True, r)
            except Exception as e:  # noqa: BLE001 - delivered to callers
                for _, _, deliver in batch:
                    deliver(False, e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped fn must take a list of inputs and return a
    list of outputs of the same length; concurrent callers each pass one
    input and receive one output."""

    def wrap(fn: Callable):
        params = fn.__code__.co_varnames[:fn.__code__.co_argcount]
        is_method = params and params[0] == "self"
        # Queues hold locks/threads, so they must be created lazily in the
        # executing process — never captured in the pickled closure.
        key = f"__serve_batch_queue_{fn.__name__}"

        if is_method:
            @functools.wraps(fn)
            def method(self, arg):
                # runtime import: locks/threads must never ride the pickle
                from ray_tpu.serve import batching as _b
                with _b._QUEUE_CREATE_LOCK:
                    q = getattr(self, key, None)
                    if q is None:
                        q = _b._BatchQueue(fn, max_batch_size,
                                           batch_wait_timeout_s)
                        setattr(self, key, q)
                return q.submit(self, arg)
            return method

        @functools.wraps(fn)
        def func(arg):
            from ray_tpu.serve import batching as _b
            qkey = (fn.__module__, fn.__qualname__)
            with _b._QUEUE_CREATE_LOCK:
                q = _b._QUEUES.get(qkey)
                if q is None:
                    q = _b._QUEUES[qkey] = _b._BatchQueue(
                        fn, max_batch_size, batch_wait_timeout_s)
            return q.submit(None, arg)
        return func

    if _fn is not None:
        return wrap(_fn)
    return wrap
