"""@serve.deployment decorator, Deployment, and bound Applications.

Analog of /root/reference/python/ray/serve/deployment.py and the
deployment-graph builder (_private/deployment_graph_build.py): ``.bind()``
captures init args — including other bound deployments, which become
DeploymentHandles at runtime — producing an Application that ``serve.run``
deploys bottom-up.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig


@dataclass
class Application:
    """A deployment bound to init args (possibly referencing other apps)."""
    deployment: "Deployment"
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)

    def _flatten(self) -> List["Application"]:
        """All applications in dependency order (dependencies first)."""
        seen: List[Application] = []

        def visit(app: Application):
            for a in list(app.init_args) + list(app.init_kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
            if app not in seen:
                seen.append(app)

        visit(self)
        return seen


class Deployment:
    def __init__(self, func_or_class: Callable, name: str,
                 config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **opts) -> "Deployment":
        cfg = DeploymentConfig(**{**self.config.__dict__})
        for k, v in opts.items():
            if k == "name":
                continue
            if not hasattr(cfg, k):
                raise ValueError(f"unknown deployment option {k!r}")
            if k == "autoscaling_config" and isinstance(v, dict):
                v = AutoscalingConfig(**v)
            setattr(cfg, k, v)
        return Deployment(self.func_or_class,
                          opts.get("name", self.name), cfg)

    def __repr__(self):
        return f"Deployment(name={self.name!r})"


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None,
               num_replicas: int = 1,
               max_concurrent_queries: int = 8,
               user_config: Optional[Any] = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None):
    """``@serve.deployment`` (cf. reference serve/api.py:251)."""

    def wrap(target):
        if isinstance(autoscaling_config, dict):
            auto = AutoscalingConfig(**autoscaling_config)
        else:
            auto = autoscaling_config
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            autoscaling_config=auto,
            ray_actor_options=dict(ray_actor_options or {}))
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
