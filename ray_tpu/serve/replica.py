"""Replica actor: wraps the user's deployment callable.

Analog of /root/reference/python/ray/serve/_private/replica.py
(RayServeReplica :250, handle_request :494): tracks in-flight queries for
autoscaling metrics, enforces max_concurrent_queries admission, supports
reconfigure(user_config) and health checks.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict


class ReplicaActor:
    """Runs as a threaded ray_tpu actor (max_concurrency =
    max_concurrent_queries + house-keeping headroom) so queries execute
    concurrently while metrics/health calls stay responsive."""

    def __init__(self, serialized_init: bytes, deployment_name: str,
                 replica_tag: str, user_config: Any = None):
        import cloudpickle
        cls_or_fn, init_args, init_kwargs = cloudpickle.loads(serialized_init)
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self._lock = threading.Lock()
        self._num_ongoing = 0
        self._num_processed = 0
        self._started = time.time()
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = cls_or_fn
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- requests
    def handle_request(self, method_name: str, args: tuple,
                       kwargs: dict) -> Any:
        with self._lock:
            self._num_ongoing += 1
        try:
            if self._is_function:
                target = self._callable
            elif method_name in ("__call__", "", None):
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._num_ongoing -= 1
                self._num_processed += 1

    # ------------------------------------------------------------- control
    def reconfigure(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def check_health(self) -> bool:
        if not self._is_function and hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True

    def get_metrics(self) -> Dict[str, Any]:
        """Queue metrics feeding the controller's autoscaling policy
        (cf. reference serve/_private/autoscaling_metrics.py)."""
        with self._lock:
            return {
                "replica_tag": self.replica_tag,
                "num_ongoing": self._num_ongoing,
                "num_processed": self._num_processed,
                "uptime_s": time.time() - self._started,
            }

    def prepare_for_shutdown(self) -> bool:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._num_ongoing == 0:
                    return True
            time.sleep(0.05)
        return False
