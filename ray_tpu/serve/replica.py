"""Replica actor: wraps the user's deployment callable.

Analog of /root/reference/python/ray/serve/_private/replica.py
(RayServeReplica :250, handle_request :494): tracks in-flight queries for
autoscaling metrics, enforces max_concurrent_queries admission, supports
reconfigure(user_config) and health checks.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict

from ray_tpu._private import runtime_metrics as rtm
from ray_tpu.util.tracing import tracing_helper as trh

_M_REQ = rtm.histogram_family(
    "ray_tpu_serve_request_ms",
    "serve request latency per deployment (ms); streaming requests are "
    "timed first call -> last yield", tag_key="deployment")
_M_ONGOING = rtm.gauge(
    "ray_tpu_serve_ongoing", "in-flight serve requests on this replica")


class ReplicaActor:
    """Runs as an *async* ray_tpu actor (handle_request is a coroutine, so
    the worker gives this actor an event loop): queries interleave at await
    points up to the actor's max_concurrency, matching the reference
    replica's asyncio execution model (replica.py:250). Sync user callables
    still work — they just occupy the loop for their duration."""

    def __init__(self, serialized_init: bytes, deployment_name: str,
                 replica_tag: str, user_config: Any = None,
                 max_concurrent_queries: int = 8):
        import cloudpickle
        from concurrent.futures import ThreadPoolExecutor
        cls_or_fn, init_args, init_kwargs = cloudpickle.loads(serialized_init)
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self._num_ongoing = 0
        self._num_processed = 0
        self._started = time.time()
        # sync user callables run here so they parallelize up to
        # max_concurrent_queries and never block the loop (metrics/health
        # stay responsive); async callables run on the loop itself
        self._sync_pool = ThreadPoolExecutor(
            max_workers=max_concurrent_queries,
            thread_name_prefix="replica-sync")
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = cls_or_fn
            self._is_function = True
        # method name -> (target, is_async): the two
        # inspect.iscoroutinefunction calls per request cost more than
        # a no-op handler at serving QPS; targets are stable for the
        # replica's lifetime
        self._targets: Dict[str, Any] = {}
        if user_config is not None:
            self.reconfigure(user_config)

    def _resolve_target(self, method_name: str):
        """(target, is_async) for one request, cached per method."""
        key = method_name or "__call__"
        hit = self._targets.get(key)
        if hit is None:
            if self._is_function or key == "__call__":
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            is_async = (inspect.iscoroutinefunction(target)
                        or inspect.iscoroutinefunction(
                            getattr(target, "__call__", None)))
            hit = self._targets[key] = (target, is_async)
        return hit

    # ------------------------------------------------------------- requests
    async def handle_request(self, method_name: str, args: tuple,
                             kwargs: dict) -> Any:
        import functools
        self._num_ongoing += 1
        _M_ONGOING.set(self._num_ongoing)
        _t0 = rtm.now()
        # replica span (docs/observability.md): the actor-call exec span
        # is named after the wrapper (task:handle_request); this one
        # names the ROUTED user method, and its context carries into the
        # user code — so the serve hop reads "<deployment>.<method>" in
        # a trace, and spans the target opens (handoff pull, import
        # wait) nest under it
        sspan = trh.open_span(
            f"serve:{self.deployment_name}.{method_name or '__call__'}",
            "serve")
        token = trh.install(sspan.ctx()) if sspan is not None else None
        try:
            target, is_async = self._resolve_target(method_name)
            if is_async:
                result = await target(*args, **kwargs)
            else:
                loop = asyncio.get_running_loop()
                call = functools.partial(target, *args, **kwargs)
                if sspan is not None:
                    # run_in_executor drops ContextVars; re-bind so the
                    # user code's spans keep the request's trace
                    call = trh.bind_ctx(sspan.ctx(), call)
                result = await loop.run_in_executor(self._sync_pool,
                                                    call)
                if inspect.isawaitable(result):  # e.g. @serve.batch future
                    result = await result
            if sspan is not None:
                sspan.end()
            return result
        except BaseException as e:
            if sspan is not None:
                sspan.end(trh.ERROR, error_type=type(e).__name__)
            raise
        finally:
            if token is not None:
                trh.uninstall(token)
            self._num_ongoing -= 1
            self._num_processed += 1
            _M_ONGOING.set(self._num_ongoing)
            _M_REQ.observe_since(self.deployment_name, _t0)

    async def handle_request_streaming(self, method_name: str, args: tuple,
                                       kwargs: dict):
        """Streaming request path: called with num_returns="streaming"
        (DeploymentHandle.remote_streaming), so every item this
        async generator yields is delivered to the caller as its own
        ObjectRef the moment it is produced — a Serve LLM request
        streams its first token while decode is still running.  The
        user target must return an (async) generator / iterable."""
        self._num_ongoing += 1
        _M_ONGOING.set(self._num_ongoing)
        _t0 = rtm.now()
        # replica span covering the whole stream (first call -> last
        # yield); the user generator's own spans nest under it
        sspan = trh.open_span(
            f"serve:{self.deployment_name}.{method_name or '__call__'}",
            "serve")
        token = trh.install(sspan.ctx()) if sspan is not None else None
        nitems = 0
        try:
            target, _ = self._resolve_target(method_name)
            result = target(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            if hasattr(result, "__aiter__"):
                async for item in result:
                    nitems += 1
                    yield item
            else:
                # sync generator: pull each (possibly blocking) step on
                # the sync pool, matching handle_request's executor
                # offload — a blocking per-item producer must not stall
                # the replica's event loop for the whole stream
                loop = asyncio.get_running_loop()
                it = iter(result)
                sentinel = object()
                while True:
                    item = await loop.run_in_executor(
                        self._sync_pool, next, it, sentinel)
                    if item is sentinel:
                        break
                    nitems += 1
                    yield item
            if sspan is not None:
                sspan.end(num_items=nitems)
        except BaseException as e:
            if sspan is not None:
                sspan.end(trh.ERROR, error_type=type(e).__name__,
                          num_items=nitems)
            raise
        finally:
            if token is not None:
                trh.uninstall(token)
            self._num_ongoing -= 1
            self._num_processed += 1
            _M_ONGOING.set(self._num_ongoing)
            _M_REQ.observe_since(self.deployment_name, _t0)

    # ------------------------------------------------------------- control
    def reconfigure(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def check_health(self) -> bool:
        if not self._is_function and hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True

    def get_metrics(self) -> Dict[str, Any]:
        """Queue metrics feeding the controller's autoscaling policy
        (cf. reference serve/_private/autoscaling_metrics.py).

        ``load``: the autoscaling signal — the user callable's
        ``autoscale_load()`` when it defines one and returns a number
        (e.g. an LLM decode pool's slot pressure, serve/llm.py),
        otherwise the in-flight request count.  ``node_id`` feeds
        locality-preferring routing (handle.py prefer_node)."""
        load = None
        prefixes = None
        if not self._is_function:
            fn = getattr(self._callable, "autoscale_load", None)
            if fn is not None:
                try:
                    # float() inside the guard: a non-numeric return
                    # must fall back, not fail the health check
                    load = float(fn())
                except Exception:
                    load = None
            # resident prompt-prefix digests (docs/serve_frontdoor.md):
            # the controller republishes them on the get_targets load
            # path so handles can prefix-affinity-route.  Advertised
            # every health-check pass — the set is the replica's CURRENT
            # cache, not a delta
            adv = getattr(self._callable, "advertised_prefixes", None)
            if adv is not None:
                try:
                    prefixes = adv()
                except Exception:
                    prefixes = None
        out = {
            "replica_tag": self.replica_tag,
            "num_ongoing": self._num_ongoing,
            "load": (load if load is not None
                     else float(self._num_ongoing)),
            "node_id": self._node_id(),
            "num_processed": self._num_processed,
            "uptime_s": time.time() - self._started,
        }
        if prefixes:
            out["prefixes"] = prefixes
        return out

    @staticmethod
    def _node_id() -> str:
        try:
            from ray_tpu.runtime.core_worker import get_global_worker
            return get_global_worker().node_id
        except Exception:
            return ""

    async def prepare_for_shutdown(self) -> bool:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self._num_ongoing == 0:
                return True
            await asyncio.sleep(0.05)
        return False
