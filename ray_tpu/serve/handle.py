"""DeploymentHandle: client-side router to a deployment's replicas.

Analog of /root/reference/python/ray/serve/handle.py (RayServeHandle :78)
+ _private/router.py (Router/ReplicaSet :261/:62, assign_replica :221):
power-of-two-choices over handle-local in-flight counts, with
max_concurrent_queries backpressure; routing tables refresh from the
controller with a version stamp (short-poll analog of LongPollClient).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE

_REFRESH_INTERVAL_S = 1.0


class _SubHandle:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._route(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._lock = threading.Condition()
        self._version = -1
        self._replicas: List[str] = []
        self._max_concurrent = 8
        self._inflight: Dict[str, int] = {}
        self._outstanding: List[tuple] = []  # (ref, replica_name)
        self._last_refresh = 0.0
        self._controller = None
        self._drain_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ plumbing
    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(
                CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        return self._controller

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_INTERVAL_S:
            return
        self._last_refresh = now
        targets = ray_tpu.get(
            self._get_controller().get_targets.remote(
                self.deployment_name, self._version), timeout=10)
        if targets is None:
            with self._lock:
                self._replicas = []
            return
        if targets.get("unchanged"):
            return
        with self._lock:
            self._version = targets["version"]
            self._replicas = targets["replicas"]
            self._max_concurrent = targets["max_concurrent_queries"]
            for r in self._replicas:
                self._inflight.setdefault(r, 0)
            self._lock.notify_all()

    def _ensure_drainer(self):
        with self._lock:
            if (self._drain_thread is None
                    or not self._drain_thread.is_alive()):
                self._drain_thread = threading.Thread(
                    target=self._drain_loop, daemon=True)
                self._drain_thread.start()

    def _drain_loop(self):
        """Decrement in-flight counts as replica calls complete. Exits when
        no requests are outstanding (restarted on demand by _route) so idle
        handles pin no thread."""
        idle_since = None
        while True:
            with self._lock:
                outstanding = list(self._outstanding)
            if not outstanding:
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > 1.0:
                    with self._lock:
                        if not self._outstanding:
                            self._drain_thread = None
                            return
                time.sleep(0.02)
                continue
            idle_since = None
            refs = [r for r, _ in outstanding]
            try:
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.2,
                                       fetch_local=False)
            except Exception:
                # transient wait failure: errored calls still complete their
                # refs, so just retry rather than zeroing in-flight counts
                time.sleep(0.1)
                continue
            if done:
                done_ids = {d.id for d in done}
                with self._lock:
                    still = []
                    for ref, replica in self._outstanding:
                        if ref.id in done_ids:
                            self._inflight[replica] = max(
                                0, self._inflight.get(replica, 1) - 1)
                        else:
                            still.append((ref, replica))
                    self._outstanding = still
                    self._lock.notify_all()

    # ------------------------------------------------------------- routing
    def _pick_replica(self) -> Optional[str]:
        """Power-of-two choices among replicas with spare concurrency."""
        candidates = [r for r in self._replicas
                      if self._inflight.get(r, 0) < self._max_concurrent]
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        a, b = random.sample(candidates, 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b

    def _route(self, method: str, args: tuple, kwargs: dict):
        self._refresh()
        deadline = time.monotonic() + 60.0
        while True:
            with self._lock:
                replica = self._pick_replica()
                if replica is not None:
                    self._inflight[replica] = \
                        self._inflight.get(replica, 0) + 1
            if replica is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replica of {self.deployment_name!r} available "
                        "(backpressure timeout)")
                with self._lock:
                    self._lock.wait(timeout=0.1)
                self._refresh(force=not self._replicas)
                continue
            try:
                actor = ray_tpu.get_actor(replica,
                                          namespace=SERVE_NAMESPACE)
                ref = actor.handle_request.remote(method, args, kwargs)
            except Exception:
                # replica vanished (scale-down/crash): drop it locally,
                # force-refresh the table, and retry until the deadline
                with self._lock:
                    self._inflight[replica] = max(
                        0, self._inflight.get(replica, 1) - 1)
                    if replica in self._replicas:
                        self._replicas.remove(replica)
                if time.monotonic() > deadline:
                    raise
                self._refresh(force=True)
                time.sleep(0.05)
                continue
            with self._lock:
                self._outstanding.append((ref, replica))
            self._ensure_drainer()
            return ref

    # ------------------------------------------------------------ user API
    def remote(self, *args, **kwargs):
        return self._route("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _SubHandle:
        if name.startswith("_"):
            raise AttributeError(name)
        return _SubHandle(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"
