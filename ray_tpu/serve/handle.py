"""DeploymentHandle: client-side router to a deployment's replicas.

Analog of /root/reference/python/ray/serve/handle.py (RayServeHandle :78)
+ _private/router.py (Router/ReplicaSet :261/:62, assign_replica :221):
power-of-two-choices over handle-local in-flight counts, with
max_concurrent_queries backpressure; routing tables refresh from the
controller with a version stamp (short-poll analog of LongPollClient).

Per-request hot path (the reference's 1-2 ms overhead bar,
doc/source/serve/performance.md:19-20): no GCS lookups (replica actor
handles are cached), no polling threads (in-flight counts decrement via
owned-object ready callbacks the moment a reply lands), and the periodic
routing-table refresh runs on a background thread so requests never wait
on the controller.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private.config import CONFIG
from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE
from ray_tpu.serve.frontdoor.prefix import PrefixIndex, page_digests
from ray_tpu.util.tracing import tracing_helper as trh

_REFRESH_INTERVAL_S = 1.0


class _SubHandle:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._route(self._method, args, kwargs)

    def remote_streaming(self, *args, **kwargs):
        return self._handle._route_streaming(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._lock = threading.Condition()
        self._version = -1
        self._replicas: List[str] = []
        self._actors: Dict[str, Any] = {}      # replica name -> actor handle
        self._max_concurrent = 8
        self._inflight: Dict[str, int] = {}
        # controller-published per-replica signals (refreshed every
        # poll, including version-unchanged replies): queue-depth load
        # for p2c routing, node ids for locality-preferring routes
        self._loads: Dict[str, float] = {}
        self._nodes: Dict[str, str] = {}
        # prefix-affinity index (docs/serve_frontdoor.md): fed from the
        # controller's load-publish path when replicas advertise
        # resident paged-KV prefix digests; lazily materialized so a
        # handle to a non-LLM deployment pays nothing
        self._prefix_index: Optional[PrefixIndex] = None
        self._prefix_page_size = 0
        self._prefix_advertisers: set = set()
        # replica name -> monotonic deadline: recently-failed replicas
        # the routing table may still list (the controller needs a few
        # health-check passes to retire a death) — skipped until the
        # deadline so retries don't bounce off the same corpse
        self._suspect: Dict[str, float] = {}
        # result (ref / streaming generator) -> replica that produced
        # it, so a consumer seeing an error AFTER submission can
        # suspect-list the right replica (mark_suspect / replica_of)
        self._ref_replica: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._last_refresh = 0.0
        self._controller = None
        self._refreshing = False
        self._worker_cache = None

    # ------------------------------------------------------------ plumbing
    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(
                CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        return self._controller

    def _maybe_refresh_bg(self):
        """Kick a background refresh when the table is stale; requests
        keep routing on the current table meanwhile."""
        now = time.monotonic()
        if now - self._last_refresh < _REFRESH_INTERVAL_S:
            return
        with self._lock:
            if self._refreshing:
                return
            self._refreshing = True
        threading.Thread(target=self._refresh_quiet, daemon=True).start()

    def _refresh_quiet(self):
        try:
            self._refresh(force=True)
        except Exception:
            pass
        finally:
            with self._lock:
                self._refreshing = False

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_INTERVAL_S:
            return
        self._last_refresh = now
        targets = ray_tpu.get(
            self._get_controller().get_targets.remote(
                self.deployment_name, self._version), timeout=10)
        if targets is None:
            with self._lock:
                self._replicas = []
                self._actors.clear()
            return
        if targets.get("unchanged"):
            # loads ride every reply: they change each health-check
            # pass without bumping the routing version
            with self._lock:
                self._loads.update(targets.get("loads") or {})
            self._feed_prefixes(targets.get("prefixes"))
            return
        self._feed_prefixes(targets.get("prefixes"))
        with self._lock:
            self._version = targets["version"]
            self._replicas = targets["replicas"]
            self._max_concurrent = targets["max_concurrent_queries"]
            self._loads = dict(targets.get("loads") or {})
            self._nodes = dict(targets.get("nodes") or {})
            # suspects for retired tags must not accumulate over
            # autoscaling churn in a long-lived handle
            now = time.monotonic()
            self._suspect = {r: d for r, d in self._suspect.items()
                             if d > now and r in self._loads}
            live = set(self._replicas)
            for r in self._replicas:
                self._inflight.setdefault(r, 0)
            for gone in [r for r in self._actors if r not in live]:
                del self._actors[gone]
            self._lock.notify_all()

    def _feed_prefixes(self, prefixes: Optional[Dict[str, dict]]) -> None:
        """Fold one controller publish of advertised prefix digests
        (replica -> {"page_size", "digests"}) into the affinity index.
        ``None`` means the deployment doesn't advertise (non-LLM, or
        the prefix cache is off) — nothing is built.  Replicas that
        stopped advertising (died, drained, cache wiped on recovery)
        are dropped so their digests can't pin new requests."""
        if prefixes is None:
            return
        idx = self._prefix_index
        if idx is None:
            idx = self._prefix_index = PrefixIndex(
                CONFIG.serve_prefix_index_max)
        for replica, adv in prefixes.items():
            ps = int(adv.get("page_size") or 0)
            if ps:
                self._prefix_page_size = ps
            idx.update(replica, adv.get("digests") or ())
        for replica in self._prefix_advertisers - set(prefixes):
            idx.drop_replica(replica)
        self._prefix_advertisers = set(prefixes)

    def prefix_route(self, prompt) -> Optional[str]:
        """Replica holding the deepest resident prefix of ``prompt``
        (docs/serve_frontdoor.md), or None.  Counts hit/miss/evicted on
        ``ray_tpu_serve_prefix_hit``; a pure no-op (no metric noise)
        until some replica has advertised."""
        idx = self._prefix_index
        ps = self._prefix_page_size
        if idx is None or ps <= 0 or not prompt:
            return None
        chain = page_digests(prompt, ps)
        if not chain:
            return None
        with self._lock:
            live = set(self._replicas)
        return idx.lookup(chain, live)

    def _actor_for(self, replica: str):
        """Cached replica actor handle: one GCS lookup per replica per
        table version, not one per request."""
        actor = self._actors.get(replica)
        if actor is None:
            actor = ray_tpu.get_actor(replica, namespace=SERVE_NAMESPACE)
            with self._lock:
                self._actors[replica] = actor
        return actor

    # ------------------------------------------------------------- routing
    def _load_score(self, r: str) -> float:
        """Effective queue depth: the replica's telemetry-published load
        (covers traffic from OTHER handles and engine-internal queues)
        plus this handle's own in-flight count (covers what we sent
        since the last health-check pass).  Handle-local counts alone
        hotspot a pool under skewed stream lengths — every handle sees
        its own short queue while one replica drowns."""
        return self._inflight.get(r, 0) + self._loads.get(r, 0.0)

    def _pick_replica(self, prefer_node: Optional[str] = None,
                      prefer_replica: Optional[str] = None
                      ) -> Optional[str]:
        """Power-of-two choices on effective queue depth among replicas
        with spare concurrency; ``prefer_node`` narrows to replicas
        colocated with that node first (e.g. the node holding a KV
        handoff's primary copy) and falls back to the whole pool —
        the cross-node loser still gets the object via the transfer
        plane's locality-aware pull, just not for free.

        ``prefer_replica`` is a hard affinity pick (a prefix-index hit:
        THAT replica holds the prompt's resident KV pages) honored
        whenever the replica is routable with spare concurrency —
        affinity beats load balance because a hit skips whole prefill
        pages; a saturated or suspect target falls back to p2c."""
        now = time.monotonic()
        candidates = [r for r in self._replicas
                      if self._inflight.get(r, 0) < self._max_concurrent
                      and self._suspect.get(r, 0.0) <= now]
        if not candidates:
            return None
        if prefer_replica is not None and prefer_replica in candidates:
            return prefer_replica
        if prefer_node:
            colocated = [r for r in candidates
                         if self._nodes.get(r) == prefer_node]
            if colocated:
                candidates = colocated
        if len(candidates) == 1:
            return candidates[0]
        a, b = random.sample(candidates, 2)
        return a if self._load_score(a) <= self._load_score(b) else b

    def _release(self, replica: str) -> None:
        with self._lock:
            self._inflight[replica] = max(
                0, self._inflight.get(replica, 1) - 1)
            self._lock.notify_all()

    def mark_suspect(self, replica: str, ttl_s: float = 10.0) -> None:
        """Skip this replica for ``ttl_s`` (an error surfaced on its
        stream/result AFTER submission succeeded, so the routing loop's
        own submit-failure handling never saw it)."""
        with self._lock:
            self._suspect[replica] = time.monotonic() + ttl_s

    def replica_of(self, result) -> Optional[str]:
        """The replica a _route result (ref / streaming generator) was
        submitted to, for mark_suspect on late-surfacing errors."""
        return self._ref_replica.get(result)

    def _route(self, method: str, args: tuple, kwargs: dict,
               prefer_replica: Optional[str] = None):
        return self._route_impl(
            lambda actor: actor.handle_request.remote(method, args, kwargs),
            prefer_replica=prefer_replica)

    def _route_streaming(self, method: str, args: tuple, kwargs: dict,
                         prefer_node: Optional[str] = None):
        """Streaming variant: submits the replica's
        handle_request_streaming with num_returns="streaming" and
        returns the live StreamingObjectRefGenerator.  The in-flight
        count drops when the whole stream completes (its completion
        sentinel resolves), so a long generation holds its concurrency
        slot for its true duration."""
        return self._route_impl(
            lambda actor: actor.handle_request_streaming.options(
                num_returns="streaming").remote(method, args, kwargs),
            prefer_node=prefer_node)

    def _route_impl(self, submit, prefer_node: Optional[str] = None,
                    prefer_replica: Optional[str] = None):
        """One routing loop for both request shapes: pick a replica
        (power-of-two choices under max_concurrent_queries), call
        ``submit(actor)``, and anchor the in-flight release on the
        result's completion — the reply ref itself, or a streaming
        generator's completion sentinel."""
        if self._replicas:
            self._maybe_refresh_bg()
        else:
            self._refresh()      # cold start: need a table before routing
        deadline = time.monotonic() + 60.0
        while True:
            with self._lock:
                replica = self._pick_replica(prefer_node, prefer_replica)
                if replica is not None:
                    self._inflight[replica] = \
                        self._inflight.get(replica, 0) + 1
            if replica is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replica of {self.deployment_name!r} available "
                        "(backpressure timeout)")
                with self._lock:
                    self._lock.wait(timeout=0.1)
                self._refresh(force=not self._replicas)
                continue
            try:
                actor = self._actor_for(replica)
                out = submit(actor)
            except Exception:
                # replica vanished (scale-down/crash): drop it locally,
                # force-refresh the table, and retry until the deadline.
                # Also suspect-listed: the refreshed table may re-add it
                # until the controller retires the death
                with self._lock:
                    self._inflight[replica] = max(
                        0, self._inflight.get(replica, 1) - 1)
                    if replica in self._replicas:
                        self._replicas.remove(replica)
                    self._actors.pop(replica, None)
                    self._suspect[replica] = time.monotonic() + 10.0
                if time.monotonic() > deadline:
                    raise
                self._refresh(force=True)
                time.sleep(0.05)
                continue
            # in-flight count drops the instant the completion lands —
            # no polling drainer between a reply and the next admission
            anchor = out.completed() if hasattr(out, "completed") else out
            try:
                self._ref_replica[out] = replica
            except TypeError:
                pass
            self._worker().add_ready_callback(
                anchor, lambda r=replica: self._release(r))
            return out

    # ------------------------------------------------------------ user API
    def _open_root(self):
        """Driver-entry trace root (docs/observability.md): opened only
        when no trace is already active (an http-proxy or disagg root
        upstream owns the request), installed for the submit section so
        the replica task joins it.  Returns (root, token, t0)."""
        if trh.current_context() is not None:
            return None, None, 0.0
        root = trh.serve_ingress_root(
            f"handle:{self.deployment_name}", route=self.deployment_name)
        if root is None:
            return None, None, 0.0
        return root, trh.install(root.ctx()), time.perf_counter()

    def _anchor_root(self, root, t0: float, out) -> None:
        """Close the root (+ TTFT SLO accounting) when the request's
        completion anchor resolves — no polling, the same ready
        callback that drops the in-flight count.  A reply that resolved
        to an error payload closes the root as a failure, not a
        (possibly fast) SLO success."""
        anchor = out.completed() if hasattr(out, "completed") else out
        pool = self.deployment_name
        worker = self._worker()

        def _done():
            if worker.result_is_error(anchor):
                trh.finish_request(root, pool=pool, route=pool,
                                   status=trh.ERROR,
                                   error_type="TaskError")
            else:
                trh.finish_request(root, pool=pool, route=pool,
                                   ttft_s=time.perf_counter() - t0)

        worker.add_ready_callback(anchor, _done)

    def remote(self, *args, **kwargs):
        root, token, t0 = self._open_root()
        try:
            out = self._route("__call__", args, kwargs)
        except Exception as e:
            trh.finish_request(root, pool=self.deployment_name,
                               status=trh.ERROR,
                               error_type=type(e).__name__)
            raise
        finally:
            if token is not None:
                trh.uninstall(token)
        if root is not None:
            self._anchor_root(root, t0, out)
        return out

    def remote_streaming(self, *args, **kwargs):
        """Route one request through the replica's streaming path:
        returns a StreamingObjectRefGenerator whose items arrive as the
        deployment's generator produces them (token streaming)."""
        root, token, t0 = self._open_root()
        try:
            out = self._route_streaming("__call__", args, kwargs)
        except Exception as e:
            trh.finish_request(root, pool=self.deployment_name,
                               status=trh.ERROR,
                               error_type=type(e).__name__)
            raise
        finally:
            if token is not None:
                trh.uninstall(token)
        if root is not None:
            # the anchor is stream COMPLETION: the root's dur is total
            # stream latency; TTFT SLO accounting belongs to token-aware
            # drivers (DisaggHandle / the llm stream consumers)
            anchor = out.completed() if hasattr(out, "completed") else out
            worker = self._worker()
            pool = self.deployment_name

            def _done():
                failed = worker.result_is_error(anchor)
                trh.finish_request(
                    root, pool=pool, route=pool,
                    status=trh.ERROR if failed else trh.OK,
                    error_type="TaskError" if failed else None)

            worker.add_ready_callback(anchor, _done)
        return out

    def try_remote(self, *args, **kwargs):
        """One-shot non-blocking route: submit to a replica with spare
        capacity, or return None (cold table, backpressure, vanished
        replica).  Event-loop callers (the HTTP proxy) use this as the
        fast path and fall back to the blocking ``remote`` in an
        executor — so the common case never leaves the loop and the
        congested case never stalls it."""
        if not self._replicas:
            return None
        self._maybe_refresh_bg()
        with self._lock:
            replica = self._pick_replica()
            if replica is None:
                return None
            self._inflight[replica] = self._inflight.get(replica, 0) + 1
        root, token, t0 = self._open_root()
        try:
            actor = self._actor_for(replica)
            ref = actor.handle_request.remote("__call__", args, kwargs)
        except Exception as e:
            # close the root like remote() does — an abandoned root
            # would drop the failed request from SLO accounting
            trh.finish_request(root, pool=self.deployment_name,
                               status=trh.ERROR,
                               error_type=type(e).__name__)
            self._release(replica)
            return None
        finally:
            if token is not None:
                trh.uninstall(token)
        self._worker().add_ready_callback(
            ref, lambda r=replica: self._release(r))
        if root is not None:
            self._anchor_root(root, t0, ref)
        return ref

    def _worker(self):
        w = self._worker_cache
        if w is None:
            from ray_tpu.runtime.core_worker import get_global_worker
            w = self._worker_cache = get_global_worker()
        return w

    def __getattr__(self, name: str) -> _SubHandle:
        if name.startswith("_"):
            raise AttributeError(name)
        return _SubHandle(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"


async def _aget(worker, ref, timeout: float = 60.0):
    """Awaitable ray_tpu.get: readiness via an owned-object ready
    callback (no polling), then an immediate local get with an executor
    fallback for store-resident results — the http_proxy fast-path
    idiom, reusable by any event-loop router."""
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    def _on_ready():
        loop.call_soon_threadsafe(
            lambda: fut.done() or fut.set_result(None))

    worker.add_ready_callback(ref, _on_ready)
    await asyncio.wait_for(fut, timeout=timeout)
    try:
        return ray_tpu.get(ref, timeout=0.05)
    except ray_tpu.exceptions.GetTimeoutError:
        return await loop.run_in_executor(
            None, lambda: ray_tpu.get(ref, timeout=timeout))


class DisaggHandle:
    """Client-side prefill->decode router for a disaggregated LLM app
    (docs/serve_disagg.md).  One ``stream()`` call:

      1. routes the request to the PREFILL pool (p2c on published
         queue depth) and yields the first token the moment the pool
         samples it — TTFT never waits for the handoff, let alone a
         decode slot;
      2. routes the KV handoff ref to the DECODE pool, preferring a
         replica colocated with the handoff object's primary copy
         (``prefer_node``), and streams the decoded tokens;
      3. re-queues on KVPoolFullError (decode pool momentarily full —
         bounded backoff, possibly landing on another replica) and
         re-prefills on replica death, surfacing a ``{"retry": n}``
         marker mid-stream; already-yielded tokens are not repeated
         (greedy decode reproduces them; sampled decode resumes with a
         fresh suffix).

    A prefill replica dying AFTER its handoff was pulled is invisible:
    the decode stream runs entirely off the imported pages."""

    def __init__(self, prefill_deployment: str, decode_deployment: str,
                 *, max_retries: int = 3,
                 pool_full_timeout_s: float = 30.0):
        self.prefill = DeploymentHandle(prefill_deployment)
        self.decode = DeploymentHandle(decode_deployment)
        self.max_retries = max_retries
        self.pool_full_timeout_s = pool_full_timeout_s

    async def stream(self, request: Dict[str, Any]):
        """Async generator: ``{"token": id}`` per token (first token
        from the prefill pool, the rest from the decode pool), optional
        ``{"retry": n}`` markers, then a summary dict.

        Tracing (docs/observability.md): the whole request is one trace
        — an ingress root here, ``prefill`` / ``decode`` hop spans per
        attempt, the replica-side execution / handoff-pull / import-wait
        spans as their children — closed with TTFT/TPOT SLO accounting.
        A request that dies mid-flight closes its root with the failure
        and the crash ``dossier_id`` when the error carries one, so the
        trace and the flight recorder cross-link.  When an upstream
        ingress already owns the request (the SSE front door installed
        its root on this task's context), no second root opens — the
        hop spans join the upstream trace and the front door closes the
        root with CLIENT-observed SLO latency (one request, one SLO
        verdict)."""
        root = None
        if trh.current_context() is None:
            root = trh.serve_ingress_root(
                f"disagg:{self.decode.deployment_name}",
                route=self.decode.deployment_name)
        t0 = time.perf_counter()
        first_tok = last_tok = None
        emitted = 0                 # tokens already yielded to the client
        retries = 0
        failure: Optional[BaseException] = None
        try:
            while True:
                try:
                    async for kind, val in self._once(request, emitted,
                                                      root):
                        if kind == "token":
                            now = time.perf_counter()
                            if first_tok is None:
                                first_tok = now
                            last_tok = now
                            emitted += 1
                            yield {"token": val}
                        else:
                            yield val
                    return
                except Exception as e:
                    if _is_pool_full(e) or retries >= self.max_retries:
                        raise
                    retries += 1
                    yield {"retry": retries, "error": type(e).__name__}
        except BaseException as e:
            failure = e
            raise
        finally:
            if root is not None:
                tpot_s = None
                if emitted > 1 and first_tok is not None:
                    tpot_s = (last_tok - first_tok) / (emitted - 1)
                if failure is None:
                    status = trh.OK
                elif isinstance(failure, (GeneratorExit,
                                          asyncio.CancelledError)):
                    # the CLIENT walked away mid-stream: not a service
                    # failure — excluded from both SLO counters
                    status = trh.CANCELLED
                else:
                    status = trh.ERROR
                trh.finish_request(
                    root, pool="disagg",
                    route=self.decode.deployment_name,
                    status=status,
                    ttft_s=(first_tok - t0)
                    if first_tok is not None else None,
                    tpot_s=tpot_s, num_tokens=emitted,
                    error_type=(type(failure).__name__
                                if failure is not None else None),
                    dossier_id=getattr(failure, "dossier_id", None))

    async def _once(self, request: Dict[str, Any], skip: int, root=None):
        """One prefill->decode attempt, yielding ("token", id) /
        ("summary", dict).  The first ``skip`` stream positions (tokens
        the client already holds from an earlier attempt) are consumed
        silently — a retry resumes the client's stream, it doesn't
        restart it."""
        worker = self.prefill._worker()
        loop = asyncio.get_running_loop()
        rctx = root.ctx() if root is not None else None
        # client-observed prefill hop: routing + queue wait + replica
        # prefill + reply; the replica-side task:prefill span nests
        # under it, so queue wait is the visible gap between the two
        sp_pref = trh.open_span("prefill", "hop", ctx=rctx)
        pctx = sp_pref.ctx() if sp_pref is not None else rctx
        # prefix-affinity (docs/serve_frontdoor.md): pin the prefill
        # hop to a replica advertising resident KV pages for this
        # prompt's deepest page-aligned prefix — a hit skips whole
        # prefill pages engine-side.  Falls back to p2c on miss or
        # when the pinned replica is saturated/suspect.
        pinned = self.prefill.prefix_route(request.get("prompt") or ())
        if sp_pref is not None and pinned is not None:
            sp_pref.set_attr("prefix_replica", pinned)
        # routing runs in an executor: _route_impl may block (capacity
        # waits, cold-table controller RPC) and this coroutine shares
        # its loop with every other stream (the http_proxy precedent);
        # bind_ctx carries the trace across the executor hop
        pref_ref = await loop.run_in_executor(
            None, trh.bind_ctx(
                pctx, lambda: self.prefill._route(
                    "prefill", (request,), {}, prefer_replica=pinned)))
        try:
            pref = await _aget(worker, pref_ref)
        except Exception as e:
            # the prefill replica died with our call on it: suspect-list
            # it so the outer retry routes around the corpse
            if sp_pref is not None:
                sp_pref.end(trh.ERROR, error_type=type(e).__name__)
            name = self.prefill.replica_of(pref_ref)
            if name:
                self.prefill.mark_suspect(name)
            raise
        if sp_pref is not None:
            sp_pref.end(prompt_len=pref.get("prompt_len"),
                        npages=pref.get("npages"))
        pos = 1                 # stream position incl. the first token
        if pos > skip:
            yield ("token", pref["first_token"])
        if pref.get("done"):
            yield ("summary", {
                "finish_reason": pref["finish_reason"],
                "num_tokens": 1, "prompt_len": pref["prompt_len"],
                "time_to_first_token_s":
                    pref["time_to_first_token_s"]})
            return
        deadline = time.monotonic() + self.pool_full_timeout_s
        backoff = 0.05
        while True:
            # one decode hop span per routed attempt (a pool-full
            # re-queue is a fresh attempt, possibly another replica)
            sp_dec = trh.open_span("decode", "hop", ctx=rctx)
            dctx = sp_dec.ctx() if sp_dec is not None else rctx
            gen = await loop.run_in_executor(
                None, trh.bind_ctx(
                    dctx, lambda: self.decode._route_streaming(
                        "decode", (pref["handoff"], request), {},
                        prefer_node=pref.get("node"))))
            try:
                async for item_ref in gen:
                    item = await _aget(worker, item_ref, timeout=60.0)
                    if "token" in item:
                        pos += 1
                        if pos > skip:
                            yield ("token", item["token"])
                    else:
                        item.setdefault(
                            "time_to_first_token_s",
                            pref["time_to_first_token_s"])
                        yield ("summary", item)
                if sp_dec is not None:
                    sp_dec.end(num_tokens=pos)
                return
            except Exception as e:
                if sp_dec is not None:
                    sp_dec.end(trh.ERROR, error_type=type(e).__name__)
                if not _is_pool_full(e):
                    # a death surfaced mid-stream: the submit succeeded,
                    # so the routing loop never saw it — suspect-list
                    # the replica before the outer retry re-routes
                    name = self.decode.replica_of(gen)
                    if name:
                        self.decode.mark_suspect(name)
                    raise
                if pos > 1 or time.monotonic() > deadline:
                    raise      # mid-stream pool-full can't happen; bail
                # decode pool momentarily full: re-queue the SAME
                # handoff (bounded backoff, p2c may pick another
                # replica) instead of wedging behind the pool
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 1.0)

    # -- convenience non-streaming API ---------------------------------
    async def generate(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Aggregate a stream() into one result dict (tokens list +
        summary), the non-streaming client shape."""
        tokens: List[int] = []
        out: Dict[str, Any] = {}
        retries = 0
        async for item in self.stream(request):
            if "token" in item:
                tokens.append(item["token"])
            elif "retry" in item:
                retries = item["retry"]
            else:
                out = dict(item)
        out["tokens"] = tokens
        if retries:
            out["retries"] = retries
        return out

    def __repr__(self):
        return (f"DisaggHandle({self.prefill.deployment_name!r} -> "
                f"{self.decode.deployment_name!r})")


def _is_pool_full(e: BaseException) -> bool:
    """KVPoolFullError, possibly wrapped by the task-error path."""
    if isinstance(e, ray_tpu.exceptions.KVPoolFullError):
        return True
    return "KVPoolFullError" in f"{type(e).__name__}: {e}"
