"""DeploymentHandle: client-side router to a deployment's replicas.

Analog of /root/reference/python/ray/serve/handle.py (RayServeHandle :78)
+ _private/router.py (Router/ReplicaSet :261/:62, assign_replica :221):
power-of-two-choices over handle-local in-flight counts, with
max_concurrent_queries backpressure; routing tables refresh from the
controller with a version stamp (short-poll analog of LongPollClient).

Per-request hot path (the reference's 1-2 ms overhead bar,
doc/source/serve/performance.md:19-20): no GCS lookups (replica actor
handles are cached), no polling threads (in-flight counts decrement via
owned-object ready callbacks the moment a reply lands), and the periodic
routing-table refresh runs on a background thread so requests never wait
on the controller.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE

_REFRESH_INTERVAL_S = 1.0


class _SubHandle:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._route(self._method, args, kwargs)

    def remote_streaming(self, *args, **kwargs):
        return self._handle._route_streaming(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._lock = threading.Condition()
        self._version = -1
        self._replicas: List[str] = []
        self._actors: Dict[str, Any] = {}      # replica name -> actor handle
        self._max_concurrent = 8
        self._inflight: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._controller = None
        self._refreshing = False
        self._worker_cache = None

    # ------------------------------------------------------------ plumbing
    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(
                CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        return self._controller

    def _maybe_refresh_bg(self):
        """Kick a background refresh when the table is stale; requests
        keep routing on the current table meanwhile."""
        now = time.monotonic()
        if now - self._last_refresh < _REFRESH_INTERVAL_S:
            return
        with self._lock:
            if self._refreshing:
                return
            self._refreshing = True
        threading.Thread(target=self._refresh_quiet, daemon=True).start()

    def _refresh_quiet(self):
        try:
            self._refresh(force=True)
        except Exception:
            pass
        finally:
            with self._lock:
                self._refreshing = False

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_INTERVAL_S:
            return
        self._last_refresh = now
        targets = ray_tpu.get(
            self._get_controller().get_targets.remote(
                self.deployment_name, self._version), timeout=10)
        if targets is None:
            with self._lock:
                self._replicas = []
                self._actors.clear()
            return
        if targets.get("unchanged"):
            return
        with self._lock:
            self._version = targets["version"]
            self._replicas = targets["replicas"]
            self._max_concurrent = targets["max_concurrent_queries"]
            live = set(self._replicas)
            for r in self._replicas:
                self._inflight.setdefault(r, 0)
            for gone in [r for r in self._actors if r not in live]:
                del self._actors[gone]
            self._lock.notify_all()

    def _actor_for(self, replica: str):
        """Cached replica actor handle: one GCS lookup per replica per
        table version, not one per request."""
        actor = self._actors.get(replica)
        if actor is None:
            actor = ray_tpu.get_actor(replica, namespace=SERVE_NAMESPACE)
            with self._lock:
                self._actors[replica] = actor
        return actor

    # ------------------------------------------------------------- routing
    def _pick_replica(self) -> Optional[str]:
        """Power-of-two choices among replicas with spare concurrency."""
        candidates = [r for r in self._replicas
                      if self._inflight.get(r, 0) < self._max_concurrent]
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        a, b = random.sample(candidates, 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b

    def _release(self, replica: str) -> None:
        with self._lock:
            self._inflight[replica] = max(
                0, self._inflight.get(replica, 1) - 1)
            self._lock.notify_all()

    def _route(self, method: str, args: tuple, kwargs: dict):
        return self._route_impl(
            lambda actor: actor.handle_request.remote(method, args, kwargs))

    def _route_streaming(self, method: str, args: tuple, kwargs: dict):
        """Streaming variant: submits the replica's
        handle_request_streaming with num_returns="streaming" and
        returns the live StreamingObjectRefGenerator.  The in-flight
        count drops when the whole stream completes (its completion
        sentinel resolves), so a long generation holds its concurrency
        slot for its true duration."""
        return self._route_impl(
            lambda actor: actor.handle_request_streaming.options(
                num_returns="streaming").remote(method, args, kwargs))

    def _route_impl(self, submit):
        """One routing loop for both request shapes: pick a replica
        (power-of-two choices under max_concurrent_queries), call
        ``submit(actor)``, and anchor the in-flight release on the
        result's completion — the reply ref itself, or a streaming
        generator's completion sentinel."""
        if self._replicas:
            self._maybe_refresh_bg()
        else:
            self._refresh()      # cold start: need a table before routing
        deadline = time.monotonic() + 60.0
        while True:
            with self._lock:
                replica = self._pick_replica()
                if replica is not None:
                    self._inflight[replica] = \
                        self._inflight.get(replica, 0) + 1
            if replica is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replica of {self.deployment_name!r} available "
                        "(backpressure timeout)")
                with self._lock:
                    self._lock.wait(timeout=0.1)
                self._refresh(force=not self._replicas)
                continue
            try:
                actor = self._actor_for(replica)
                out = submit(actor)
            except Exception:
                # replica vanished (scale-down/crash): drop it locally,
                # force-refresh the table, and retry until the deadline
                with self._lock:
                    self._inflight[replica] = max(
                        0, self._inflight.get(replica, 1) - 1)
                    if replica in self._replicas:
                        self._replicas.remove(replica)
                    self._actors.pop(replica, None)
                if time.monotonic() > deadline:
                    raise
                self._refresh(force=True)
                time.sleep(0.05)
                continue
            # in-flight count drops the instant the completion lands —
            # no polling drainer between a reply and the next admission
            anchor = out.completed() if hasattr(out, "completed") else out
            self._worker().add_ready_callback(
                anchor, lambda r=replica: self._release(r))
            return out

    # ------------------------------------------------------------ user API
    def remote(self, *args, **kwargs):
        return self._route("__call__", args, kwargs)

    def remote_streaming(self, *args, **kwargs):
        """Route one request through the replica's streaming path:
        returns a StreamingObjectRefGenerator whose items arrive as the
        deployment's generator produces them (token streaming)."""
        return self._route_streaming("__call__", args, kwargs)

    def try_remote(self, *args, **kwargs):
        """One-shot non-blocking route: submit to a replica with spare
        capacity, or return None (cold table, backpressure, vanished
        replica).  Event-loop callers (the HTTP proxy) use this as the
        fast path and fall back to the blocking ``remote`` in an
        executor — so the common case never leaves the loop and the
        congested case never stalls it."""
        if not self._replicas:
            return None
        self._maybe_refresh_bg()
        with self._lock:
            replica = self._pick_replica()
            if replica is None:
                return None
            self._inflight[replica] = self._inflight.get(replica, 0) + 1
        try:
            actor = self._actor_for(replica)
            ref = actor.handle_request.remote("__call__", args, kwargs)
        except Exception:
            self._release(replica)
            return None
        self._worker().add_ready_callback(
            ref, lambda r=replica: self._release(r))
        return ref

    def _worker(self):
        w = self._worker_cache
        if w is None:
            from ray_tpu.runtime.core_worker import get_global_worker
            w = self._worker_cache = get_global_worker()
        return w

    def __getattr__(self, name: str) -> _SubHandle:
        if name.startswith("_"):
            raise AttributeError(name)
        return _SubHandle(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"
