"""Sharded train-state construction and the pjit train step.

This is the compute heart of JaxTrainer: where the reference's
DataParallelTrainer wires torch DDP around a user loop
(/root/reference/python/ray/train/data_parallel_trainer.py:329,
torch/config.py:29 — NCCL process groups), here the *entire* parallelism
strategy (DP/FSDP/TP/CP) is carried by shardings on one jitted step function
and XLA emits the ICI/DCN collectives.

Flow:
  1. ``jax.eval_shape`` the state constructor with params still boxed in
     ``nn.Partitioned`` metadata (optax state inherits the boxes),
  2. read logical PartitionSpecs off the abstract tree, map them through the
     rule table to mesh axes,
  3. jit the constructor with ``out_shardings`` so parameters are *born
     sharded* (no host-memory spike, no broadcast), and
  4. jit the step with donated state for in-place buffers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state as flax_train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.ops.losses import chunked_lm_loss, softmax_cross_entropy
from ray_tpu.parallel.sharding import (LOGICAL_RULES, ShardingRules,
                                       logical_spec, tree_mesh_shardings)

TrainState = flax_train_state.TrainState


def _decay_mask(params: Any) -> Any:
    """Weight-decay only matmul kernels / embeddings, by parameter *name* —
    ndim is unreliable once nn.scan stacks per-layer 1-D norm scales to 2-D."""
    def fn(path, _):
        keys = {k.key for k in path if hasattr(k, "key")}
        return bool(keys & {"kernel", "embed"})
    return jax.tree_util.tree_map_with_path(fn, params)


@dataclasses.dataclass
class OptimizerConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    decay_steps: int = 10000
    min_lr_ratio: float = 0.1
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    accum_steps: int = 1
    # "bfloat16" halves the first-moment buffer — the standard memory-lean
    # setting for fitting bigger models per chip (second moment stays fp32)
    mu_dtype: str = "float32"
    # "adafactor" replaces AdamW's two full-size moments with factored
    # row/col statistics (Shazeer & Stern) — the TPU-native memory-lean
    # optimizer (T5 heritage) that fits ~1B params on a 16 GiB chip
    optimizer: str = "adamw"

    def make(self) -> optax.GradientTransformation:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, self.learning_rate, self.warmup_steps,
            max(self.decay_steps, self.warmup_steps + 1),
            self.learning_rate * self.min_lr_ratio)
        if self.optimizer == "adafactor":
            # optax applies adafactor's weight_decay_rate as a RAW per-step
            # multiplicative decay (not lr-scaled, unlike adamw's decoupled
            # decay): passing 0.1 would shrink kernels by 10% per step and
            # collapse the model.  Approximate decoupled decay with
            # lr * weight_decay, the AdamW-equivalent magnitude at peak lr.
            decay = (self.weight_decay * self.learning_rate
                     if self.weight_decay else None)
            tx = optax.chain(
                optax.clip_by_global_norm(self.grad_clip),
                optax.adafactor(schedule, min_dim_size_to_factor=128,
                                weight_decay_rate=decay,
                                weight_decay_mask=_decay_mask),
            )
            if self.accum_steps > 1:
                tx = optax.MultiSteps(tx, self.accum_steps)
            return tx
        tx = optax.chain(
            optax.clip_by_global_norm(self.grad_clip),
            optax.adamw(schedule, b1=self.b1, b2=self.b2,
                        weight_decay=self.weight_decay,
                        mu_dtype=self.mu_dtype,
                        mask=_decay_mask),
        )
        if self.accum_steps > 1:
            tx = optax.MultiSteps(tx, self.accum_steps)
        return tx


def _lm_loss_body(batch: Dict[str, jax.Array],
                  head: Callable) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Shared next-token plumbing: slice tokens/mask, run the model via
    ``head(inputs, mask, targets) -> (loss, denom, mutated)``, thread the
    MoE routers' sown aux losses (ray_tpu/ops/moe.py) into the total."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:].astype(jnp.float32)
    loss, denom, mutated = head(inputs, mask, targets)
    metrics = {"loss": loss, "tokens": denom}
    aux_leaves = [jnp.sum(a) for path, a in jax.tree_util.tree_leaves_with_path(
        mutated.get("intermediates", {})) if "moe_aux_loss" in str(path)]
    if aux_leaves:
        aux = sum(aux_leaves)
        loss = loss + aux
        metrics["moe_aux_loss"] = aux
        metrics["loss"] = loss
    return loss, metrics


def cast_params_once(params: Any, dtype=jnp.bfloat16) -> Any:
    """Cast f32 matrix/embedding params to the activation dtype OUTSIDE
    the rematted blocks.

    flax promotes param dtype inside each Dense call — under full remat
    that cast sits inside the checkpointed region and re-reads the f32
    master weights on every backward recompute (~6.5 GB of extra HBM
    traffic per recompute at 1B params).  Hoisting it here makes the
    bf16 copy a saved residual: one cast per step, measured +1.4pp MFU
    on gpt-large with remat_policy="nothing" (benchmarks/mfu_sweep.py).
    1-D leaves (norm scales) stay f32 — their kernels want f32 anyway.
    Gradients are unchanged: autodiff through the cast accumulates f32.
    """
    return jax.tree.map(
        lambda p: p.astype(dtype)
        if (hasattr(p, "dtype") and p.dtype == jnp.float32
            and getattr(p, "ndim", 0) >= 2) else p, params)


def lm_loss_fn(apply_fn: Callable, params: Any, batch: Dict[str, jax.Array],
               z_loss: float = 0.0,
               param_cast=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token LM loss. batch: {"tokens": [B, S+1] or [B, S], "mask"?}.

    ``param_cast``: optional dtype for :func:`cast_params_once` (models
    computing in bf16 with f32 masters under remat)."""
    if param_cast is not None:
        params = cast_params_once(params, param_cast)

    def head(inputs, mask, targets):
        logits, mutated = apply_fn({"params": params}, inputs,
                                   mutable=["intermediates"])
        loss, denom = softmax_cross_entropy(logits, targets, mask, z_loss)
        return loss, denom, mutated

    return _lm_loss_body(batch, head)


def lm_loss_chunked_fn(apply_fn: Callable, params: Any,
                       batch: Dict[str, jax.Array],
                       z_loss: float = 0.0,
                       chunk_size: int = 256,
                       head_weight: Optional[Callable] = None,
                       param_cast=None
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token LM loss with the chunked projection head
    (ops/losses.py chunked_lm_loss): the logits tensor's peak HBM drops
    by ~S/chunk_size, enabling larger per-chip batches. Same batch
    contract as lm_loss_fn; the model must support
    ``apply(..., return_hidden=True)`` (GPT does).

    ``head_weight(params) -> (weight, transpose_weight)`` selects the
    projection weight. The default follows GPT's naming — an untied
    ``lm_head`` Dense, else the tied ``embed`` table — and raises for
    models that match neither; pass an explicit selector (e.g. via
    functools.partial) for other architectures.

    ``param_cast``: optional dtype for :func:`cast_params_once`.
    """
    if param_cast is not None:
        params = cast_params_once(params, param_cast)

    def head(inputs, mask, targets):
        hidden, mutated = apply_fn({"params": params}, inputs,
                                   mutable=["intermediates"],
                                   return_hidden=True)
        raw = nn.meta.unbox(params)
        if head_weight is not None:
            weight, transpose = head_weight(raw)
        elif "lm_head" in raw:
            weight, transpose = raw["lm_head"]["kernel"], False
        elif "embed" in raw:
            weight, transpose = raw["embed"], True
        else:
            raise ValueError(
                "lm_loss_chunked_fn could not find the projection head "
                "(no 'lm_head' or 'embed' in params); pass head_weight=")
        loss, denom = chunked_lm_loss(hidden, weight, targets, mask,
                                      z_loss, chunk_size,
                                      transpose_weight=transpose)
        return loss, denom, mutated

    return _lm_loss_body(batch, head)


def trace_state_shardings(build_state, example_batch, mesh: Mesh,
                          rules: ShardingRules, batch_axes=("batch",)):
    """Trace the state abstractly and map its logical PartitionSpecs to
    mesh shardings.  Returns (state_shardings, batch_sharding) — the
    contract both the fused step below and the sharded executor's split
    grad/apply step (train/sharded/executor.py) build on."""
    if example_batch is None:
        raise ValueError("example_batch is required to trace shapes")
    abstract = jax.eval_shape(build_state, jax.random.PRNGKey(0),
                              example_batch)
    logical = nn.get_partition_spec(abstract)
    state_shardings = tree_mesh_shardings(logical, mesh, rules)

    # optimizer states that don't mirror the param's shape (adafactor's
    # factored v_row/v_col, scalar counters) still inherit the param's
    # logical spec from the boxed metadata; a spec longer than the leaf's
    # rank is invalid — replicate those
    def _fit_rank(sh, leaf):
        ndim = getattr(leaf, "ndim", None)
        if ndim is not None and hasattr(sh, "spec") and len(sh.spec) > ndim:
            return NamedSharding(mesh, PartitionSpec())
        return sh

    state_shardings = jax.tree.map(_fit_rank, state_shardings,
                                   nn.meta.unbox(abstract))
    batch_sharding = jax.tree.map(
        lambda _: NamedSharding(mesh, logical_spec(batch_axes, mesh, rules)),
        example_batch)
    return state_shardings, batch_sharding


def _born_sharded(build_state, step, example_batch, mesh: Mesh,
                  rules: ShardingRules, batch_axes=("batch",)):
    """Shared construction: trace the state abstractly, read logical
    PartitionSpecs, jit init (born sharded) and step (donated state)."""
    state_shardings, batch_sharding = trace_state_shardings(
        build_state, example_batch, mesh, rules, batch_axes)
    repl = NamedSharding(mesh, PartitionSpec())
    init_fn = jax.jit(build_state, out_shardings=state_shardings)
    step_fn = jax.jit(
        step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, repl),
        donate_argnums=(0,),
    )
    return init_fn, step_fn, state_shardings, batch_sharding


def make_sharded_train(model: nn.Module,
                       mesh: Mesh,
                       optimizer: Optional[OptimizerConfig] = None,
                       rules: ShardingRules = LOGICAL_RULES,
                       loss_fn: Callable = lm_loss_fn,
                       example_batch: Optional[Dict[str, jax.Array]] = None,
                       z_loss: Optional[float] = None,
                       init_inputs: Optional[Callable] = None):
    """Returns (init_fn, step_fn, state_shardings, batch_sharding).

    ``init_fn(rng, batch) -> TrainState`` born sharded over ``mesh``;
    ``step_fn(state, batch) -> (state, metrics)`` jitted with donated state.
    ``init_inputs(batch) -> args tuple`` overrides how model.init is called
    (default: next-token LM convention, ``batch["tokens"][:, :-1]``).
    """
    optimizer = optimizer or OptimizerConfig()
    tx = optimizer.make()
    if z_loss is None:
        z_loss = getattr(getattr(model, "cfg", None), "z_loss", 0.0)

    def build_state(rng, batch) -> TrainState:
        if init_inputs is not None:
            variables = model.init(rng, *init_inputs(batch))
        else:
            variables = model.init(rng, batch["tokens"][:, :-1])
        return TrainState.create(apply_fn=model.apply,
                                 params=variables["params"], tx=tx)

    def step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, Any]]:
        grad_fn = jax.value_and_grad(
            lambda p: loss_fn(state.apply_fn, p, batch, z_loss), has_aux=True)
        (loss, metrics), grads = grad_fn(state.params)
        new_state = state.apply_gradients(grads=grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    return _born_sharded(build_state, step, example_batch, mesh, rules,
                         batch_axes=("batch", None))


def classification_loss_fn(logits: jax.Array, labels: jax.Array
                           ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Softmax CE + accuracy for label classification (vision models)."""
    one_hot = jax.nn.one_hot(labels, logits.shape[-1])
    loss = jnp.mean(optax.softmax_cross_entropy(logits, one_hot))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}


class TrainStateBN(TrainState):
    """TrainState plus mutable normalization statistics (BatchNorm)."""

    batch_stats: Any = None


def make_vision_train(model: nn.Module,
                      mesh: Mesh,
                      optimizer: Optional[OptimizerConfig] = None,
                      rules: ShardingRules = LOGICAL_RULES,
                      example_batch: Optional[Dict[str, jax.Array]] = None):
    """make_sharded_train for image classifiers with BatchNorm state.

    batch: {"image": [B, H, W, C], "label": [B]}.  Same born-sharded
    construction as make_sharded_train; the step threads ``batch_stats``
    through the jitted update (cf. flax imagenet example semantics, built
    on this repo's sharding rules).
    """
    optimizer = optimizer or OptimizerConfig()
    tx = optimizer.make()
    if example_batch is None:
        raise ValueError("example_batch is required to trace shapes")

    def build_state(rng, batch) -> TrainStateBN:
        variables = model.init(rng, batch["image"])
        return TrainStateBN.create(
            apply_fn=model.apply, params=variables["params"], tx=tx,
            batch_stats=variables.get("batch_stats", {}))

    def step(state: TrainStateBN, batch):
        def lf(p):
            logits, mutated = state.apply_fn(
                {"params": p, "batch_stats": state.batch_stats},
                batch["image"], mutable=["batch_stats"])
            loss, metrics = classification_loss_fn(logits, batch["label"])
            return loss, (metrics, mutated.get("batch_stats", {}))

        (loss, (metrics, new_stats)), grads = jax.value_and_grad(
            lf, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads).replace(
            batch_stats=new_stats)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    # batch leaves have mixed rank (image rank-4, label rank-1): shard dim 0
    # only, trailing dims stay unsharded implicitly
    return _born_sharded(build_state, step, example_batch, mesh, rules,
                         batch_axes=("batch",))
