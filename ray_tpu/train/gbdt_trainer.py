"""GBDT trainers: gradient-boosted trees over Datasets.

Analog of /root/reference/python/ray/train/gbdt_trainer.py (GBDTTrainer)
and its xgboost/lightgbm subclasses (xgboost_trainer.py / lightgbm_trainer.py,
backed by xgboost-ray/lightgbm-ray actors).  Backend resolution: xgboost or
lightgbm when importable, else the always-available sklearn
HistGradientBoosting models — the image bakes sklearn but not xgboost, so
the default path works everywhere and the premium backends light up when
installed.

Training runs inside one remote actor sized by ScalingConfig (boosted-tree
fitting is not data-parallel the way SGD is; the reference's distributed
tree building needs xgboost's own RABIT collective, which rides our
collective group API when xgboost is present).  Dataset shards are
materialized to numpy on the actor; fit() returns an air.Result whose
checkpoint holds the fitted booster for SklearnPredictor/BatchPredictor.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train.predictor import Predictor

MODEL_KEY = "model"


def _dataset_to_xy(ds, label_column: str):
    batch = ds.to_numpy() if hasattr(ds, "to_numpy") else ds
    y = np.asarray(batch[label_column])
    feature_cols = sorted(k for k in batch.keys() if k != label_column)
    x = np.column_stack([np.asarray(batch[c]).reshape(len(y), -1)
                         for c in feature_cols])
    return x, y, feature_cols


def _fit_booster(backend: str, objective: str, params: Dict[str, Any],
                 x, y, eval_sets):
    """Train one booster; returns (model, eval_metrics_per_iteration)."""
    if backend == "xgboost":
        import xgboost as xgb
        dtrain = xgb.DMatrix(x, label=y)
        evals = [(xgb.DMatrix(ex, label=ey), name)
                 for name, (ex, ey) in eval_sets.items()]
        evals_result: Dict[str, Any] = {}
        model = xgb.train(params, dtrain,
                          num_boost_round=params.pop("num_boost_round", 100),
                          evals=evals, evals_result=evals_result,
                          verbose_eval=False)
        return model, evals_result
    if backend == "lightgbm":
        import lightgbm as lgb
        train_set = lgb.Dataset(x, label=y)
        valid = [lgb.Dataset(ex, label=ey) for ex, ey in eval_sets.values()]
        evals_result: Dict[str, Any] = {}
        model = lgb.train(params, train_set, valid_sets=valid,
                          callbacks=[lgb.record_evaluation(evals_result)])
        return model, evals_result
    # sklearn backend (always available in this image)
    from sklearn.ensemble import (HistGradientBoostingClassifier,
                                  HistGradientBoostingRegressor)
    cls = HistGradientBoostingRegressor if objective == "regression" \
        else HistGradientBoostingClassifier
    model = cls(**params)
    model.fit(x, y)
    metrics = {}
    for name, (ex, ey) in eval_sets.items():
        metrics[name] = {"score": float(model.score(ex, ey))}
    return model, metrics


class GBDTTrainer(BaseTrainer):
    """Boosted-tree trainer over ray_tpu Datasets.

    datasets must include "train"; any other keys become eval sets.
    """

    _backend = "auto"

    def __init__(self, *, label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 objective: str = "classification",
                 num_workers_hint: int = 1,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        if "train" not in self.datasets:
            raise ValueError('datasets must include a "train" dataset')
        self.label_column = label_column
        self.params = dict(params or {})
        self.objective = objective
        self.num_workers_hint = num_workers_hint

    @classmethod
    def _resolve_backend(cls) -> str:
        if cls._backend != "auto":
            return cls._backend
        for mod in ("xgboost", "lightgbm"):
            try:
                __import__(mod)
                return mod
            except ImportError:
                continue
        return "sklearn"

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        self.params.update(config)

    def fit(self) -> Result:
        import ray_tpu

        backend = self._resolve_backend()
        label, objective, params = self.label_column, self.objective, \
            dict(self.params)
        cpus = max(self.scaling_config.num_workers or 1, 1)

        # materialize train/eval splits to numpy dicts driver-side (blocks
        # stay in the object store until the fit task pulls them)
        xy = {name: _dataset_to_xy(ds, label)
              for name, ds in self.datasets.items()}

        @ray_tpu.remote(num_cpus=cpus)
        def _fit(xy_map):
            x, y, feature_cols = xy_map["train"]
            eval_sets = {n: (ex, ey) for n, (ex, ey, _) in xy_map.items()
                         if n != "train"}
            model, eval_metrics = _fit_booster(backend, objective, params,
                                               x, y, eval_sets)
            return model, eval_metrics, feature_cols

        model, eval_metrics, feature_cols = ray_tpu.get(
            _fit.remote(xy), timeout=None)
        checkpoint = Checkpoint.from_dict({
            MODEL_KEY: model,
            "label_column": label,
            "feature_columns": feature_cols,
            "backend": backend,
        })
        metrics: Dict[str, Any] = {"backend": backend}
        for name, m in eval_metrics.items():
            for k, v in m.items():
                leaf = v[-1] if isinstance(v, list) else v
                metrics[f"{name}-{k}"] = leaf
        return Result(metrics=metrics, checkpoint=checkpoint, error=None)

    def _iter_results(self):
        result = self.fit()
        yield result.metrics, result.checkpoint


class XGBoostTrainer(GBDTTrainer):
    """Reference XGBoostTrainer parity; requires xgboost installed."""

    _backend = "xgboost"


class LightGBMTrainer(GBDTTrainer):
    """Reference LightGBMTrainer parity; requires lightgbm installed."""

    _backend = "lightgbm"


class SklearnPredictor(Predictor):
    """Scores GBDTTrainer checkpoints (cf. reference sklearn predictor)."""

    def __init__(self, model, feature_columns: List[str],
                 output_column: str = "predictions"):
        self.model = model
        self.feature_columns = feature_columns
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **kwargs) -> "SklearnPredictor":
        data = checkpoint.to_dict()
        return cls(data[MODEL_KEY], data.get("feature_columns") or [],
                   **kwargs)

    def predict(self, batch: Dict[str, np.ndarray], **kwargs) -> Dict[str, np.ndarray]:
        cols = self.feature_columns or sorted(batch.keys())
        n = len(np.asarray(batch[cols[0]]))
        x = np.column_stack([np.asarray(batch[c]).reshape(n, -1)
                             for c in cols])
        out = dict(batch)
        model = self.model
        if hasattr(model, "predict"):
            out[self.output_column] = np.asarray(model.predict(x))
        else:  # raw xgboost Booster
            import xgboost as xgb
            out[self.output_column] = np.asarray(
                model.predict(xgb.DMatrix(x)))
        return out
