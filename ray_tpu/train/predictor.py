"""Predictors: checkpoint -> batch inference, locally or over a Dataset.

Analog of /root/reference/python/ray/train/predictor.py (Predictor) and
batch_predictor.py (BatchPredictor: map_batches with an actor pool so each
actor deserializes the model once).  TPU-shaped: JaxPredictor jits the
apply function on first call; BatchPredictor rides Dataset.map_batches'
stateful-actor path, so scoring N blocks costs one model load per actor,
not per block.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Base: subclass implements ``from_checkpoint`` and ``predict``."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray], **kwargs) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Runs a flax module's apply with checkpointed params.

    ``checkpoint`` must hold {"params": pytree} (e.g. Checkpoint.from_jax of
    a train state); input batches use ``input_column`` and predictions are
    written to ``output_column``.
    """

    def __init__(self, model, params: Any, *, input_column: str = "features",
                 output_column: str = "predictions",
                 extra_collections: Optional[Dict[str, Any]] = None,
                 apply_fn: Optional[Callable] = None):
        import jax
        self.model = model
        self.params = params
        # batch_stats etc. — models with normalization state must be built
        # in eval mode (e.g. ResNet(train=False)) so apply reads, not writes
        self.extra_collections = dict(extra_collections or {})
        self.input_column = input_column
        self.output_column = output_column
        raw = apply_fn or (
            lambda variables, x: model.apply(variables, x))
        self._apply = jax.jit(raw)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, model=None,
                        **kwargs) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get("params")
        extras = {k: v for k, v in data.items()
                  if k in ("batch_stats",) and v}
        if params is None and "state" in data:
            state = data["state"]
            params = getattr(state, "params", None)
            stats = getattr(state, "batch_stats", None)
            if stats:
                extras["batch_stats"] = stats
        if params is None:
            raise ValueError("checkpoint has no 'params' entry")
        if model is None:
            model = data.get("model")
        if model is None:
            raise ValueError("pass model= or store it in the checkpoint")
        return cls(model, params, extra_collections=extras, **kwargs)

    def predict(self, batch: Dict[str, np.ndarray], **kwargs) -> Dict[str, np.ndarray]:
        x = np.asarray(batch[self.input_column])
        variables = {"params": self.params, **self.extra_collections}
        out = np.asarray(self._apply(variables, x))
        result = dict(batch)
        result[self.output_column] = out
        return result


class BatchPredictor:
    """Distributed inference: score a Dataset with an actor pool.

    ``BatchPredictor.from_checkpoint(ckpt, JaxPredictor, model=...)``
    then ``.predict(ds)`` — one predictor per pool actor (reference
    batch_predictor.py semantics).
    """

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, ds, *, batch_size: Optional[int] = 4096,
                min_scoring_workers: int = 1,
                max_scoring_workers: int = 2,
                num_cpus_per_worker: float = 1.0):
        from ray_tpu.data.dataset import ActorPoolStrategy
        ckpt, pcls, pkw = self.checkpoint, self.predictor_cls, \
            self.predictor_kwargs

        class _Scorer:
            def __init__(self):
                self._p = pcls.from_checkpoint(ckpt, **pkw)

            def __call__(self, batch):
                return self._p.predict(batch)

        return ds.map_batches(
            _Scorer, batch_size=batch_size, batch_format="numpy",
            compute=ActorPoolStrategy(min_scoring_workers,
                                      max_scoring_workers),
            num_cpus=num_cpus_per_worker)

    def predict_pipelined(self, ds, *, blocks_per_window: int = 10, **kwargs):
        """Windowed scoring over a DatasetPipeline (streaming ingest)."""
        ckpt, pcls, pkw = self.checkpoint, self.predictor_cls, \
            self.predictor_kwargs
        holder: Dict[str, Predictor] = {}

        def score(batch):
            # one predictor per scoring process, not per batch
            if "p" not in holder:
                holder["p"] = pcls.from_checkpoint(ckpt, **pkw)
            return holder["p"].predict(batch)

        pipe = ds.window(blocks_per_window=blocks_per_window)
        return pipe.map_batches(score, batch_format="numpy")
