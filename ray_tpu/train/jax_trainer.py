"""JaxTrainer: the flagship TPU trainer.

The TPU-native analog of the reference's TorchTrainer
(/root/reference/python/ray/train/torch/torch_trainer.py +
torch/config.py:29): where the reference rendezvouses torch.distributed
process groups and wraps the model in DDP, JaxConfig rendezvouses
``jax.distributed`` across one-actor-per-host, and the parallelism itself
(DP/FSDP/TP/CP/EP) lives in the mesh + shardings compiled into the user's
step function (see ray_tpu.train.step.make_sharded_train).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.config import CONFIG
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.base_trainer import (BackendConfig, DataParallelTrainer,
                                        WorkerGroup)

_local = threading.local()


class JaxConfig(BackendConfig):
    """Sets up the jax.distributed coordination service over the group.

    On a real pod each worker owns its host's chips (libtpu: one process per
    host); in tests each worker sees the 8 virtual CPU devices of its own
    process — ``world_size=1`` exercises real meshes, multi-worker exercises
    the rendezvous path.

    ``host_collective`` (default on for multi-worker gangs) additionally
    rendezvouses a DCN collective group over the workers
    (docs/collective.md), so loops whose gradient reduction is NOT
    compiled into the step — workers running separate JAX runtimes,
    cross-slice sync — go through the host data plane's ``allreduce``
    via :func:`ray_tpu.train.sync_gradients`.
    """

    def __init__(self, init_distributed: bool = True,
                 platform: Optional[str] = None,
                 host_collective: bool = True):
        self.init_distributed = init_distributed
        # force a backend on the workers (e.g. "cpu" to rendezvous a
        # multi-process gloo mesh in tests / on chipless hosts); None
        # keeps whatever the worker environment selects (libtpu on pods)
        self.platform = platform
        self.host_collective = host_collective
        self._group_name: Optional[str] = None

    def on_start(self, worker_group: WorkerGroup,
                 scaling: ScalingConfig) -> None:
        if scaling.num_workers > 1 and self.host_collective:
            # unique name per run: the nonce-namespaced rendezvous makes
            # even name reuse safe, but a fresh name keeps concurrent
            # trainers in one cluster from colliding at all
            self._group_name = f"train-{uuid.uuid4().hex[:8]}"
            worker_group.execute("init_host_collective",
                                 scaling.num_workers, self._group_name)
        if not self.init_distributed or scaling.num_workers <= 1:
            return
        if self.platform:
            worker_group.execute("set_env",
                                 {"JAX_PLATFORMS": self.platform})
        ip = worker_group.execute_single(0, "get_node_ip")
        port = worker_group.execute_single(0, "find_free_port")
        coordinator = f"{ip}:{port}"
        worker_group.execute("setup_jax_distributed", coordinator)

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        if self._group_name is not None:
            try:
                worker_group.execute("destroy_host_collective",
                                     self._group_name)
            except Exception:
                pass
            self._group_name = None
        try:
            worker_group.execute("shutdown_jax_distributed")
        except Exception:
            pass


class JaxTrainer(DataParallelTrainer):
    """``JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1,
    mesh_shape={"data": 2, "fsdp": 4}))``; inside the loop use
    :func:`get_mesh` and ``air.session`` APIs."""

    backend_config_cls = JaxConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        scaling_config = scaling_config or ScalingConfig()
        mesh_shape = (dict(scaling_config.mesh_shape)
                      if scaling_config.mesh_shape else None)
        user_fn = train_loop_per_worker

        def _loop(config):
            set_loop_mesh_shape(mesh_shape)
            import inspect
            try:
                takes = len(inspect.signature(user_fn).parameters) > 0
            except (TypeError, ValueError):
                takes = True
            return user_fn(config) if takes else user_fn()

        _loop.__name__ = getattr(user_fn, "__name__", "train_loop")
        super().__init__(
            _loop,
            train_loop_config=dict(train_loop_config or {}),
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


def sync_gradients(tree: Any, *, group_name: Optional[str] = None,
                   op: str = "sum", average: bool = True) -> Any:
    """Gradient sync over the gang's host (DCN) collective group.

    Flattens a pytree of arrays, buckets the leaves into ONE contiguous
    buffer per dtype (one ``allreduce`` per dtype instead of one per
    leaf — the classic gradient-bucketing trick), reduces the buckets
    through :func:`ray_tpu.util.collective.allreduce` (pipelined ring /
    hierarchical shm data plane, docs/collective.md) and unflattens.
    ``average=True`` divides float results by the world size.

    Inside a :class:`JaxTrainer` loop the group set up by ``JaxConfig``
    (``host_collective=True``) is found automatically; no-op when no
    group exists (single-worker runs).
    """
    import jax
    import numpy as np
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu._private import step_stats
    from ray_tpu.util import collective as col

    group_name = group_name or os.environ.get(
        "RAY_TPU_TRAIN_COLLECTIVE_GROUP", "")
    if not group_name or not col.is_group_initialized(group_name):
        return tree
    world = col.get_collective_group_size(group_name)
    if world <= 1:
        return tree
    # training performance plane: the reduction is one step phase — if
    # the loop's StepClock has a step open this lands inside it, else
    # in the run ledger's out-of-step totals (docs/observability.md)
    _t0 = rtm.now()
    try:
        return _sync_gradients_timed(tree, group_name, op, average,
                                     world, jax, np, col)
    finally:
        step_stats.record_phase("grad_allreduce",
                                (rtm.now() - _t0) * 1000.0)


def _sync_gradients_timed(tree, group_name, op, average, world, jax,
                          np, col):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrs = [np.asarray(leaf) for leaf in leaves]
    by_dtype: Dict[Any, list] = {}
    for idx, a in enumerate(arrs):
        by_dtype.setdefault(a.dtype, []).append(idx)
    out = list(arrs)
    for dtype, idxs in by_dtype.items():
        # allreduce never mutates its input (ring/rd copy internally,
        # the shm arena reads slab-side): single-leaf buckets need no
        # defensive copy
        bucket = np.concatenate(
            [arrs[i].reshape(-1) for i in idxs]) if len(idxs) > 1 \
            else arrs[idxs[0]].reshape(-1)
        reduced = col.allreduce(bucket, group_name, op)
        if average and op == "sum" and np.issubdtype(dtype, np.floating):
            reduced = reduced / world
        off = 0
        for i in idxs:
            n = arrs[i].size
            out[i] = reduced[off:off + n].reshape(arrs[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def get_mesh(mesh_shape: Optional[Dict[str, int]] = None):
    """Build (and cache, per train-loop) the device mesh for this run.

    Inside a JaxTrainer loop, reads the mesh shape from the trainer's
    ScalingConfig when not given explicitly. Axis sizes of -1 absorb
    remaining devices.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if mesh_shape is None:
        mesh_shape = getattr(_local, "mesh_shape", None) or {}
    cached = getattr(_local, "mesh", None)
    if cached is not None and getattr(_local, "mesh_shape", None) == mesh_shape:
        return cached

    n = jax.device_count()
    if not mesh_shape:
        # the configurable default layout ({"data": -1} unless
        # overridden): -1 absorbs every device below
        mesh_shape = dict(CONFIG.mesh_default_axes) or {"data": n}
    names = list(mesh_shape.keys())
    sizes = list(mesh_shape.values())
    wild = [i for i, v in enumerate(sizes) if v == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = 1
    for v in sizes:
        if v != -1:
            fixed *= v
    if wild:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    else:
        total = 1
        for v in sizes:
            total *= v
        if total != n:
            raise ValueError(
                f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                f"have {n}")
    devices = mesh_utils.create_device_mesh(tuple(sizes))
    mesh = Mesh(devices, tuple(names))
    _local.mesh = mesh
    _local.mesh_shape = dict(zip(names, sizes))
    return mesh


def set_loop_mesh_shape(shape: Optional[Dict[str, int]]) -> None:
    _local.mesh_shape = shape
    _local.mesh = None
