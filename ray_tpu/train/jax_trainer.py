"""JaxTrainer: the flagship TPU trainer.

The TPU-native analog of the reference's TorchTrainer
(/root/reference/python/ray/train/torch/torch_trainer.py +
torch/config.py:29): where the reference rendezvouses torch.distributed
process groups and wraps the model in DDP, JaxConfig rendezvouses
``jax.distributed`` across one-actor-per-host, and the parallelism itself
(DP/FSDP/TP/CP/EP) lives in the mesh + shardings compiled into the user's
step function (see ray_tpu.train.step.make_sharded_train).
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.base_trainer import (BackendConfig, DataParallelTrainer,
                                        WorkerGroup)


class JaxConfig(BackendConfig):
    """Sets up the jax.distributed coordination service over the group.

    On a real pod each worker owns its host's chips (libtpu: one process per
    host); in tests each worker sees the 8 virtual CPU devices of its own
    process — ``world_size=1`` exercises real meshes, multi-worker exercises
    the rendezvous path.

    ``host_collective`` (default on for multi-worker gangs) additionally
    rendezvouses a DCN collective group over the workers
    (docs/collective.md), so loops whose gradient reduction is NOT
    compiled into the step — workers running separate JAX runtimes,
    cross-slice sync — go through the host data plane's ``allreduce``
    via :func:`ray_tpu.train.sync_gradients`.
    """

    def __init__(self, init_distributed: bool = True,
                 platform: Optional[str] = None,
                 host_collective: bool = True):
        self.init_distributed = init_distributed
        # force a backend on the workers (e.g. "cpu" to rendezvous a
        # multi-process gloo mesh in tests / on chipless hosts); None
        # keeps whatever the worker environment selects (libtpu on pods)
        self.platform = platform
        self.host_collective = host_collective
        self._group_name: Optional[str] = None

    def on_start(self, worker_group: WorkerGroup,
                 scaling: ScalingConfig) -> None:
        if scaling.num_workers > 1 and self.host_collective:
            # unique name per run: the nonce-namespaced rendezvous makes
            # even name reuse safe, but a fresh name keeps concurrent
            # trainers in one cluster from colliding at all
            self._group_name = f"train-{uuid.uuid4().hex[:8]}"
            worker_group.execute("init_host_collective",
                                 scaling.num_workers, self._group_name)
        if not self.init_distributed or scaling.num_workers <= 1:
            return
        if self.platform:
            worker_group.execute("set_env",
                                 {"JAX_PLATFORMS": self.platform})
        ip = worker_group.execute_single(0, "get_node_ip")
        port = worker_group.execute_single(0, "find_free_port")
        coordinator = f"{ip}:{port}"
        worker_group.execute("setup_jax_distributed", coordinator)

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        if self._group_name is not None:
            try:
                worker_group.execute("destroy_host_collective",
                                     self._group_name)
            except Exception:
                pass
            self._group_name = None
        try:
            worker_group.execute("shutdown_jax_distributed")
        except Exception:
            pass


class JaxTrainer(DataParallelTrainer):
    """``JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1,
    mesh_shape={"data": 2, "fsdp": 4}))``; inside the loop use
    :func:`get_mesh` and ``air.session`` APIs."""

    backend_config_cls = JaxConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        scaling_config = scaling_config or ScalingConfig()
        mesh_shape = (dict(scaling_config.mesh_shape)
                      if scaling_config.mesh_shape else None)
        user_fn = train_loop_per_worker

        def _loop(config):
            set_loop_mesh_shape(mesh_shape)
            import inspect
            try:
                takes = len(inspect.signature(user_fn).parameters) > 0
            except (TypeError, ValueError):
                takes = True
            return user_fn(config) if takes else user_fn()

        _loop.__name__ = getattr(user_fn, "__name__", "train_loop")
        super().__init__(
            _loop,
            train_loop_config=dict(train_loop_config or {}),
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


class PendingSync:
    """An in-flight gradient sync from ``sync_gradients(...,
    async_op=True)``: the bucketed allreduces run on the collective
    group's async worker while the caller keeps computing (the rest of
    backward, optimizer prep).  :meth:`wait` is the fence — it blocks
    until every bucket resolves and assembles the reduced pytree; the
    collective telemetry records how much ring time the overlap hid
    (``ray_tpu_collective_overlap_hidden_ms``)."""

    def __init__(self, assemble, handles, record: bool):
        self._assemble = assemble
        self._handles = handles
        self._record = record
        self._result = None
        self._resolved = handles is None

    @classmethod
    def ready(cls, tree) -> "PendingSync":
        """A pre-resolved sync (no group / single worker)."""
        p = cls(None, None, False)
        p._result = tree
        return p

    def done(self) -> bool:
        return self._resolved or all(h.done() for h in self._handles)

    def wait(self, timeout: Optional[float] = None) -> Any:
        if self._resolved:
            return self._result
        if self._record:
            from ray_tpu._private import runtime_metrics as rtm
            from ray_tpu._private import step_stats
            t0 = rtm.now()
            try:
                self._result = self._assemble(timeout)
            finally:
                # only the BLOCKED time lands in the step phase — the
                # hidden portion already paid for itself
                step_stats.record_phase("grad_allreduce",
                                        (rtm.now() - t0) * 1000.0)
        else:
            self._result = self._assemble(timeout)
        self._resolved = True
        self._assemble = self._handles = None
        return self._result


def sync_gradients(tree: Any, *, group_name: Optional[str] = None,
                   op: str = "sum", average: bool = True,
                   quantize: Optional[str] = None,
                   async_op: bool = False) -> Any:
    """Gradient sync over the gang's host (DCN) collective group.

    Flattens a pytree of arrays, buckets the leaves into contiguous
    per-dtype buffers capped at ``CONFIG.collective_bucket_bytes``
    apiece (the classic gradient-bucketing trick, sized so several
    buckets pipeline through the ring), reduces them through
    :func:`ray_tpu.util.collective.allreduce` (pipelined ring /
    hierarchical data plane, docs/collective.md) and unflattens.
    ``average=True`` divides float results by the world size.

    ``quantize="int8"`` ships each bucket over the wire as block-scaled
    int8 (~4x fewer DCN bytes; bounded-error numerics contract in
    docs/collective.md — accumulation stays fp32).  ``async_op=True``
    returns a :class:`PendingSync` immediately instead of blocking:
    buckets reduce on the group's async worker while backward finishes,
    and ``.wait()`` is the fence that assembles the reduced tree.

    Inside a :class:`JaxTrainer` loop the group set up by ``JaxConfig``
    (``host_collective=True``) is found automatically; no-op when no
    group exists (single-worker runs).
    """
    import jax
    import numpy as np
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu._private import step_stats
    from ray_tpu.util import collective as col

    group_name = group_name or os.environ.get(
        "RAY_TPU_TRAIN_COLLECTIVE_GROUP", "")
    if not group_name or not col.is_group_initialized(group_name):
        return PendingSync.ready(tree) if async_op else tree
    world = col.get_collective_group_size(group_name)
    if world <= 1:
        return PendingSync.ready(tree) if async_op else tree
    # training performance plane: the reduction is one step phase — if
    # the loop's StepClock has a step open this lands inside it, else
    # in the run ledger's out-of-step totals (docs/observability.md)
    _t0 = rtm.now()
    pending = _sync_gradients_issue(tree, group_name, op, average,
                                    world, quantize, async_op, jax, np,
                                    col)
    if async_op:
        # issue cost rides the caller's compute; wait() records the
        # blocked remainder as the step's grad_allreduce phase
        return pending
    try:
        return pending.wait()
    finally:
        step_stats.record_phase("grad_allreduce",
                                (rtm.now() - _t0) * 1000.0)


def _sync_gradients_issue(tree, group_name, op, average, world, quantize,
                          async_op, jax, np, col):
    from ray_tpu._private.config import CONFIG

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrs = [np.asarray(leaf) for leaf in leaves]
    by_dtype: Dict[Any, list] = {}
    for idx, a in enumerate(arrs):
        by_dtype.setdefault(a.dtype, []).append(idx)
    max_b = max(1, int(CONFIG.collective_bucket_bytes))
    plans = []  # (dtype, leaf-idx subset, AsyncWork) per sub-bucket
    for dtype, idxs in by_dtype.items():
        # split each dtype's leaves into sub-buckets of at most
        # collective_bucket_bytes: every sub-bucket is one async op, so
        # the first bucket's ring traffic starts while later buckets
        # are still being concatenated (and, with async_op, while the
        # caller is still computing)
        groups: list = []
        cur, cur_bytes = [], 0
        for i in idxs:
            if cur and cur_bytes + arrs[i].nbytes > max_b:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += arrs[i].nbytes
        if cur:
            groups.append(cur)
        for g in groups:
            if len(g) > 1:
                bucket = np.concatenate([arrs[i].reshape(-1) for i in g])
            elif async_op:
                # the async worker reads the buffer after this call
                # returns — own the bytes in case the caller reuses its
                # gradient storage mid-flight
                bucket = np.array(arrs[g[0]].reshape(-1), copy=True)
            else:
                # sync path: allreduce never mutates its input (ring/rd
                # copy internally, the shm arena reads slab-side)
                bucket = arrs[g[0]].reshape(-1)
            h = col.allreduce_async(bucket, group_name, op,
                                    quantize=quantize)
            plans.append((dtype, g, h))

    def assemble(timeout):
        col.wait_all([h for _, _, h in plans], timeout=timeout)
        out = list(arrs)
        for dtype, g, h in plans:
            reduced = h.result()
            if average and op == "sum" \
                    and np.issubdtype(dtype, np.floating):
                reduced = reduced / world
            off = 0
            for i in g:
                n = arrs[i].size
                out[i] = reduced[off:off + n].reshape(arrs[i].shape)
                off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    return PendingSync(assemble, [h for _, _, h in plans],
                       record=async_op)


# The mesh authority moved to the layout planner
# (ray_tpu/train/sharded/layout.py): one code path resolves ScalingConfig
# mesh shapes, ShardingConfigs and the MULTICHIP dryrun layouts.  These
# re-exports keep the historical `from ray_tpu.train import get_mesh`
# spelling working.
from ray_tpu.train.sharded.layout import (get_mesh,  # noqa: F401,E402
                                          set_loop_mesh_shape)
