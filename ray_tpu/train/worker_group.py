"""WorkerGroup: a gang of trainer actors, one per host.

Analog of /root/reference/python/ray/train/_internal/worker_group.py:92 and
backend_executor.py:42. Differences born of the TPU process model
(SURVEY.md §7 hard-part 4): exactly one process per host owns the chips, so
the group is placed with one bundle per host (STRICT_SPREAD on real pods)
and each worker is both "the" TPU process and the train-loop host.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air import session as air_session


class TrainWorker:
    """Actor body: runs the user train loop in a thread with an AIR session
    installed, and exposes a poll-based result channel to the driver."""

    def __init__(self, world_rank: int, world_size: int,
                 local_rank: int = 0, local_world_size: int = 1,
                 node_rank: int = 0):
        # the deployment image's sitecustomize may force a TPU platform
        # programmatically; re-assert the caller's JAX_PLATFORMS choice so
        # CPU-simulated meshes (tests, dry runs) see their virtual devices
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            import jax
            jax.config.update("jax_platforms", plat)
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self._thread: Optional[threading.Thread] = None
        self._session: Optional[air_session._Session] = None
        self._final: Any = None
        self._error: Optional[str] = None
        self._done = threading.Event()

    # -- rendezvous helpers ------------------------------------------------
    def get_node_ip(self) -> str:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
            s.close()
            return ip
        except Exception:
            return "127.0.0.1"

    def find_free_port(self) -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def set_env(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def setup_jax_distributed(self, coordinator: str) -> int:
        """Join the jax.distributed coordination service (multi-host). The
        TPU-native replacement for the reference's torch.distributed TCP
        rendezvous (train/torch/config.py:29). Returns local device count."""
        import jax
        # re-pin the platform: set_env may have changed JAX_PLATFORMS
        # after __init__ ran (plugin discovery overrides the plain env
        # var, so the pin must go through jax.config)
        plat = os.environ.get("JAX_PLATFORMS", "")
        if plat:
            jax.config.update("jax_platforms", plat)
        if plat.split(",")[0] == "cpu":
            # cross-process collectives on the CPU backend need an
            # explicit implementation; harmless when single-process
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        if self.world_size > 1:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=self.world_size,
                process_id=self.world_rank)
        return jax.local_device_count()

    def device_count(self) -> int:
        import jax
        return jax.device_count()

    def get_runtime_node_id(self) -> str:
        """The ray_tpu node hosting this rank: the driver's gang watch
        matches NODE_PREEMPTING/NODE_DEAD events against these ids
        (docs/fault_tolerance.md)."""
        try:
            from ray_tpu.runtime import core_worker as cw
            return cw.get_global_worker().node_id
        except Exception:
            return ""

    # -- host (DCN) collectives -------------------------------------------
    def init_host_collective(self, world_size: int,
                             group_name: str) -> None:
        """Join the gang's host-collective group (docs/collective.md):
        the DCN plane gradient sync / weight broadcast ride when the
        reduction isn't compiled into the step (cross-runtime workers,
        cross-slice sync).  The group name is exported so
        :func:`ray_tpu.train.sync_gradients` finds it from inside the
        user train loop."""
        from ray_tpu.util import collective as col
        col.init_collective_group(world_size, self.world_rank,
                                  group_name=group_name)
        os.environ["RAY_TPU_TRAIN_COLLECTIVE_GROUP"] = group_name

    def destroy_host_collective(self, group_name: str) -> None:
        from ray_tpu.util import collective as col
        try:
            col.destroy_collective_group(group_name)
        finally:
            os.environ.pop("RAY_TPU_TRAIN_COLLECTIVE_GROUP", None)

    def host_allreduce(self, arr, op: str = "sum", quantize=None):
        """Debug/test hook: one allreduce on the gang's host group."""
        from ray_tpu.util import collective as col
        return col.allreduce(
            arr, os.environ["RAY_TPU_TRAIN_COLLECTIVE_GROUP"], op,
            quantize=quantize)

    # -- train loop lifecycle ---------------------------------------------
    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       *, trial_name: str = "", trial_id: str = "",
                       trial_dir: str = "",
                       experiment_name: str = "",
                       checkpoint=None,
                       dataset_shard=None) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("training already running on this worker")
        self._done.clear()
        self._error = None
        self._final = None
        shards = {"train": dataset_shard} if dataset_shard is not None else {}
        self._session = air_session.init_session(
            world_rank=self.world_rank, world_size=self.world_size,
            local_rank=self.local_rank,
            local_world_size=self.local_world_size,
            node_rank=self.node_rank,
            trial_name=trial_name, trial_id=trial_id, trial_dir=trial_dir,
            experiment_name=experiment_name,
            dataset_shards=shards, checkpoint=checkpoint)
        # init_session registered under THIS (actor RPC) thread; the runner
        # thread re-registers under its own id below — drop this entry so the
        # process holds exactly one session and get_session()'s any-thread
        # fallback works for user helper threads
        with air_session._session_lock:
            air_session._sessions.pop(threading.get_ident(), None)
        sess = self._session
        run_id = trial_id or uuid.uuid4().hex[:8]
        self._run_id = run_id

        def runner():
            from ray_tpu._private import step_stats
            with air_session._session_lock:
                air_session._sessions[threading.get_ident()] = sess
            try:
                run = self._start_step_stats(run_id, experiment_name)
            except Exception:
                run = None   # observability must never fail the loop
            try:
                takes_config = True
                try:
                    import inspect
                    takes_config = len(
                        inspect.signature(train_fn).parameters) > 0
                except (TypeError, ValueError):
                    pass
                self._final = train_fn(config) if takes_config else train_fn()
            except StopIteration:
                pass
            except BaseException:
                self._error = traceback.format_exc()
            finally:
                try:
                    step_stats.end_run(run)
                except Exception:
                    pass
                self._done.set()
                with air_session._session_lock:
                    air_session._sessions.pop(threading.get_ident(), None)

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name=f"train_loop_r{self.world_rank}")
        self._thread.start()

    def _start_step_stats(self, run_id: str, experiment_name: str):
        """Open this rank's training-performance-plane run context
        (docs/observability.md): per-step phase clocks + goodput ledger,
        reports riding the worker's GCS client into the cluster step
        table.  The rank metadata (worker id + RPC address) lets
        ``ray-tpu profile --group`` gang-fan-out to every rank."""
        from ray_tpu._private import step_stats
        group = os.environ.get("RAY_TPU_TRAIN_COLLECTIVE_GROUP", "") \
            or experiment_name
        sink = None
        meta = {"world": self.world_size, "pid": os.getpid()}
        try:
            from ray_tpu.runtime import core_worker as cw
            worker = cw.get_global_worker()
        except Exception:
            worker = None
        if worker is not None:
            gcs = worker.gcs
            meta.update(worker_id=worker.worker_id.hex(),
                        node_id=worker.node_id,
                        address=list(worker.address))

            def sink(reports):
                gcs.call("report_step_stats", {"reports": reports},
                         timeout=5)
        return step_stats.start_run(
            run_id, group=group, rank=self.world_rank,
            world=self.world_size, sink=sink, meta=meta)

    def training_run_id(self) -> Optional[str]:
        return getattr(self, "_run_id", None)

    def next_result(self, timeout: float = 2.0):
        """Poll one reported (metrics, checkpoint) item, or status sentinels:
        ("done", final_return) / ("error", traceback) / ("timeout",)."""
        sess = self._session
        if sess is not None:
            item = sess.next_result(timeout=0 if self._done.is_set()
                                    else timeout)
            if item is not None:
                metrics, ckpt = item
                return ("result", metrics, ckpt)
        if self._done.is_set():
            if self._error is not None:
                return ("error", self._error)
            return ("done", self._final)
        return ("timeout",)

    def request_stop(self) -> None:
        if self._session is not None:
            self._session.stop_requested.set()
            # unblock a report() waiting for consumption
            self._session._consumed.set()

    def is_done(self) -> bool:
        return self._done.is_set()

    def health_check(self) -> bool:
        return True

    def shutdown_jax_distributed(self) -> None:
        try:
            import jax
            if self.world_size > 1:
                jax.distributed.shutdown()
        except Exception:
            pass


class WorkerGroup:
    """Driver-side handle to N TrainWorker actors placed one-per-bundle in a
    placement group."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK"):
        import ray_tpu
        from ray_tpu.util.placement_group import placement_group
        from ray_tpu.util.scheduling_strategies import \
            PlacementGroupSchedulingStrategy

        import time as _time
        self.num_workers = num_workers
        self.created_ts = _time.time()   # gang-watch event horizon
        res = dict(resources_per_worker or {"CPU": 1.0})
        self.pg = placement_group([dict(res) for _ in range(num_workers)],
                                  strategy=placement_strategy)
        if not self.pg.wait(timeout_seconds=60):
            raise TimeoutError(
                f"placement group for {num_workers} train workers "
                f"({res}) not placed in 60s — cluster too small?")
        cpus = res.pop("CPU", 1.0)
        tpus = res.pop("TPU", 0.0)
        cls = ray_tpu.remote(num_cpus=cpus, num_tpus=tpus,
                             resources=res or None)(TrainWorker)
        self.workers = []
        for rank in range(num_workers):
            strategy = PlacementGroupSchedulingStrategy(
                placement_group=self.pg, placement_group_bundle_index=rank)
            self.workers.append(
                cls.options(scheduling_strategy=strategy).remote(
                    world_rank=rank, world_size=num_workers,
                    node_rank=rank))

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call ``method`` on every worker, gather results in rank order."""
        import ray_tpu
        refs = [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]
        return ray_tpu.get(refs)

    def node_ids(self) -> List[str]:
        """ray_tpu node ids hosting the gang, in rank order (cached:
        the gang never migrates within one incarnation)."""
        if not getattr(self, "_node_ids", None):
            self._node_ids = self.execute("get_runtime_node_id")
        return list(self._node_ids)

    def execute_single(self, rank: int, method: str, *args, **kwargs) -> Any:
        import ray_tpu
        return ray_tpu.get(
            getattr(self.workers[rank], method).remote(*args, **kwargs))

    def shutdown(self) -> None:
        import ray_tpu
        from ray_tpu.util.placement_group import remove_placement_group
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
        self.workers = []
