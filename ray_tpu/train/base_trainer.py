"""BaseTrainer / DataParallelTrainer: the fit() driver loop.

Analog of /root/reference/python/ray/train/base_trainer.py:339 (fit) and
data_parallel_trainer.py:329 (training_loop). The reference routes fit()
through Tune's TrialRunner even for a single run; here fit() drives the
WorkerGroup directly and ``as_trainable()`` exposes the same run to the
Tuner for sweeps — one mechanism, two entry points.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendConfig:
    """Per-framework worker-group setup hooks (cf. reference
    train/backend_config.py)."""

    def on_start(self, worker_group: WorkerGroup,
                 scaling: ScalingConfig) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        pass


class BaseTrainer:
    def __init__(self, *,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def training_loop(self) -> None:
        raise NotImplementedError

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """A Tune function-trainable that runs this trainer once per trial;
        the trial config is merged into the train loop config."""
        trainer = self

        def _trainable(config: Dict[str, Any]):
            from ray_tpu.air import session
            import copy
            t = copy.copy(trainer)
            overrides = dict(config)
            t._apply_trial_config(overrides)
            for metrics, ckpt in t._iter_results():
                session.report(metrics, checkpoint=ckpt)

        _trainable.__name__ = type(self).__name__
        return _trainable

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        pass

    def _iter_results(self):
        raise NotImplementedError


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker`` on a WorkerGroup, streaming reported
    results back; rank-0's metrics are the canonical series."""

    backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self.backend_config_cls()

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        merged = dict(self.train_loop_config)
        merged.update(config.get("train_loop_config", config))
        self.train_loop_config = merged

    # -- driver loop -------------------------------------------------------
    def _start_group(self, experiment_name: str) -> WorkerGroup:
        sc = self.scaling_config
        group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy)
        self.backend_config.on_start(group, sc)
        shards = self._split_dataset(sc.num_workers)
        trial_id = uuid.uuid4().hex[:8]
        for rank, w in enumerate(group.workers):
            w.start_training.remote(
                self.train_loop_per_worker, self.train_loop_config,
                experiment_name=experiment_name,
                trial_id=trial_id,
                checkpoint=self.resume_from_checkpoint,
                dataset_shard=shards[rank])
        return group

    def _split_dataset(self, n: int) -> List[Any]:
        ds = self.datasets.get("train")
        if ds is None:
            return [None] * n
        if hasattr(ds, "split"):
            try:
                return ds.split(n, equal=True)
            except TypeError:
                return ds.split(n)
        return [ds] * n

    def _iter_results(self):
        """Yield (metrics, checkpoint) pairs as workers report, with
        FailureConfig-driven whole-group restarts on worker death."""
        failure = self.run_config.failure_config
        retries_left = failure.max_failures
        name = self.run_config.name or type(self).__name__.lower()
        while True:
            group = self._start_group(name)
            try:
                yield from self._poll_group(group)
                return
            except TrainingFailedError:
                if retries_left == 0:
                    raise
                if retries_left > 0:
                    retries_left -= 1
                time.sleep(1.0)
            finally:
                self.backend_config.on_shutdown(group)
                group.shutdown()

    def _poll_group(self, group: WorkerGroup):
        import ray_tpu
        done: List[Any] = [None] * len(group.workers)
        while True:
            round_items: List[Any] = []
            for rank, w in enumerate(group.workers):
                if done[rank] is not None:
                    continue
                try:
                    item = ray_tpu.get(w.next_result.remote(timeout=10.0),
                                       timeout=120.0)
                except Exception as e:
                    raise TrainingFailedError(
                        f"worker {rank} died: {e}") from e
                if item[0] == "error":
                    raise TrainingFailedError(
                        f"train loop failed on worker {rank}:\n{item[1]}")
                if item[0] == "done":
                    done[rank] = ("done", item[1])
                elif item[0] == "result":
                    round_items.append((rank, item[1], item[2]))
            if all(d is not None for d in done):
                return
            for rank, metrics, ckpt in round_items:
                if rank == 0:
                    yield metrics, ckpt

    def fit(self) -> Result:
        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        exp_dir = os.path.join(
            self.run_config.storage_path,
            self.run_config.name or f"{type(self).__name__}_"
                                    f"{time.strftime('%Y%m%d_%H%M%S')}")
        os.makedirs(exp_dir, exist_ok=True)
        last_metrics: Dict[str, Any] = {}
        kept: List[Any] = []   # (score, Checkpoint, metrics)
        error: Optional[Exception] = None
        try:
            for metrics, ckpt in self._iter_results():
                last_metrics = metrics
                if ckpt is not None:
                    kept.append((self._score(metrics, ckpt_cfg), ckpt,
                                 metrics))
                    kept = self._prune(kept, ckpt_cfg)
                if self._should_stop(metrics):
                    break
        except TrainingFailedError as e:
            error = e
        best = kept[-1][1] if kept else None
        if kept and ckpt_cfg.checkpoint_score_attribute:
            ordered = sorted(kept, key=lambda t: t[0],
                             reverse=ckpt_cfg.checkpoint_score_order == "max")
            best = ordered[0][1]
        # training failures come back on the Result (Tune-style); callers
        # that want an exception check result.error
        return Result(metrics=last_metrics, checkpoint=best, error=error,
                      log_dir=exp_dir,
                      best_checkpoints=[(c, m) for _, c, m in kept])

    def _score(self, metrics: Dict[str, Any], cfg: CheckpointConfig):
        attr = cfg.checkpoint_score_attribute
        if attr and attr in metrics:
            return metrics[attr]
        return metrics.get("training_iteration", 0)

    def _prune(self, kept: List[Any], cfg: CheckpointConfig) -> List[Any]:
        if cfg.num_to_keep is None or len(kept) <= cfg.num_to_keep:
            return kept
        if cfg.checkpoint_score_attribute:
            kept = sorted(kept, key=lambda t: t[0],
                          reverse=cfg.checkpoint_score_order == "max")
            return kept[:cfg.num_to_keep]
        return kept[-cfg.num_to_keep:]

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        for k, v in stop.items():
            if k in metrics and metrics[k] >= v:
                return True
        return False
