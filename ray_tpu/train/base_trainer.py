"""BaseTrainer / DataParallelTrainer: the fit() driver loop.

Analog of /root/reference/python/ray/train/base_trainer.py:339 (fit) and
data_parallel_trainer.py:329 (training_loop). The reference routes fit()
through Tune's TrialRunner even for a single run; here fit() drives the
WorkerGroup directly and ``as_trainable()`` exposes the same run to the
Tuner for sweeps — one mechanism, two entry points.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendConfig:
    """Per-framework worker-group setup hooks (cf. reference
    train/backend_config.py)."""

    def on_start(self, worker_group: WorkerGroup,
                 scaling: ScalingConfig) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        pass


class _GangWatch:
    """Event-plane rank-death detector for one gang incarnation
    (docs/fault_tolerance.md): polls the GCS cluster-event table (rate
    limited to ~1/s) for NODE_PREEMPTING/NODE_DEAD events naming a node
    that hosts a gang rank, raising TrainingFailedError so the driver
    fails over proactively — a graceful preemption notice triggers the
    restart DURING the grace window instead of after the node's
    heartbeats lapse.  Everything here is best effort: a broken watch
    degrades to the poll-RPC failure path, never to a wedged driver."""

    WATCHED = ("NODE_PREEMPTING", "NODE_DEAD")

    def __init__(self, group: WorkerGroup):
        self._start_ts = getattr(group, "created_ts", time.time())
        self._nodes: set = set()
        self._gcs = None
        self._last = 0.0
        try:
            self._nodes = {n for n in group.node_ids() if n}
            from ray_tpu.runtime.core_worker import get_global_worker
            self._gcs = get_global_worker().gcs
        except Exception:
            self._gcs = None

    def check(self) -> None:
        now = time.monotonic()
        if self._gcs is None or not self._nodes or now - self._last < 1.0:
            return
        self._last = now
        for etype in self.WATCHED:
            try:
                events = self._gcs.call(
                    "list_cluster_events",
                    {"type": etype, "limit": 200}, timeout=5)
            except Exception:
                return
            for ev in events or ():
                # 5s skew allowance: event ts is the emitting host's
                # wall clock.  Safe to widen — a pre-incarnation event
                # can only name a node placement already excluded from
                # THIS gang (draining/dead nodes host no new ranks).
                if ev.get("node_id") in self._nodes and \
                        ev.get("ts", 0) >= self._start_ts - 5.0:
                    raise TrainingFailedError(
                        f"gang node {str(ev.get('node_id'))[:12]} "
                        f"{etype} (event plane): {ev.get('message', '')}")


class BaseTrainer:
    def __init__(self, *,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def training_loop(self) -> None:
        raise NotImplementedError

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """A Tune function-trainable that runs this trainer once per trial;
        the trial config is merged into the train loop config."""
        trainer = self

        def _trainable(config: Dict[str, Any]):
            from ray_tpu.air import session
            import copy
            t = copy.copy(trainer)
            overrides = dict(config)
            t._apply_trial_config(overrides)
            for metrics, ckpt in t._iter_results():
                session.report(metrics, checkpoint=ckpt)

        _trainable.__name__ = type(self).__name__
        return _trainable

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        pass

    def _iter_results(self):
        raise NotImplementedError


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker`` on a WorkerGroup, streaming reported
    results back; rank-0's metrics are the canonical series."""

    backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self.backend_config_cls()
        # elastic recovery state (docs/fault_tolerance.md): the newest
        # checkpoint any report carried — a gang restart resumes from
        # it, bounding lost work to one checkpoint interval
        self._latest_checkpoint: Optional[Checkpoint] = None
        self._last_failure: str = ""
        # last "step" any rank reported: with the checkpoint's resume
        # step this prices a failover in re-executed steps — the lost
        # work the recovery auditor (metrics_history.py) ledgers
        self._last_step: Optional[int] = None

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        merged = dict(self.train_loop_config)
        merged.update(config.get("train_loop_config", config))
        self.train_loop_config = merged

    # -- driver loop -------------------------------------------------------
    def _start_group(self, experiment_name: str) -> WorkerGroup:
        sc = self.scaling_config
        group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy)
        self.backend_config.on_start(group, sc)
        shards = self._split_dataset(sc.num_workers)
        trial_id = uuid.uuid4().hex[:8]
        # keep the refs: a failed start_training (bad loop pickle, dead
        # rank) must surface as TrainingFailedError in _poll_group, not
        # livelock the poll loop on eternal ("timeout",) results
        group._start_refs = [
            w.start_training.remote(
                self.train_loop_per_worker, self.train_loop_config,
                experiment_name=experiment_name,
                trial_id=trial_id,
                checkpoint=self.resume_from_checkpoint,
                dataset_shard=shards[rank])
            for rank, w in enumerate(group.workers)]
        return group

    def _split_dataset(self, n: int) -> List[Any]:
        ds = self.datasets.get("train")
        if ds is None:
            return [None] * n
        if hasattr(ds, "split"):
            try:
                return ds.split(n, equal=True)
            except TypeError:
                return ds.split(n)
        return [ds] * n

    def _iter_results(self):
        """Yield (metrics, checkpoint) pairs as workers report, with
        FailureConfig-driven whole-group restarts on rank/node death.

        Gang recovery (docs/fault_tolerance.md): rank death is detected
        both by the poll RPCs failing and — earlier — by the event
        plane (NODE_PREEMPTING/NODE_DEAD naming a gang node, via
        _GangWatch).  On failure the group is torn down, a fresh gang
        is spawned on a new placement group (re-reserved on surviving /
        replacement nodes; a fresh collective incarnation nonce comes
        with the backend's on_start), and the loop resumes from the
        LATEST checkpoint any report carried — lost work is bounded by
        the checkpoint interval, not the run length."""
        failure = self.run_config.failure_config
        retries_left = failure.max_failures
        name = self.run_config.name or type(self).__name__.lower()
        attempt = 0
        t_failed = None
        while True:
            group = None
            try:
                group = self._start_group(name)
                if attempt:
                    self._emit_recovery(name, attempt, t_failed)
                for metrics, ckpt in self._poll_group(group):
                    if ckpt is not None:
                        self._latest_checkpoint = ckpt
                    if isinstance(metrics, dict) and \
                            isinstance(metrics.get("step"), int):
                        self._last_step = metrics["step"]
                    yield metrics, ckpt
                return
            except TrainingFailedError as e:
                if retries_left == 0:
                    raise
                if retries_left > 0:
                    retries_left -= 1
                self._last_failure = str(e)
                t_failed = time.monotonic()
                if self._latest_checkpoint is not None:
                    self.resume_from_checkpoint = self._latest_checkpoint
                attempt += 1
                time.sleep(1.0)
            except Exception as e:
                # gang RE-formation failed (pg reservation timeout while
                # the replacement slice still provisions, rendezvous
                # error): retryable like a rank death.  A first-attempt
                # failure stays fatal — that is a configuration error,
                # not a failover.
                if attempt == 0 or retries_left == 0:
                    raise
                if retries_left > 0:
                    retries_left -= 1
                self._last_failure = f"gang re-formation failed: {e}"
                attempt += 1
                time.sleep(5.0)
            finally:
                if group is not None:
                    self.backend_config.on_shutdown(group)
                    group.shutdown()

    def _emit_recovery(self, name: str, attempt: int,
                       t_failed: Optional[float]) -> None:
        """TRAIN_GANG_RECOVERY into the event plane once the replacement
        gang is spawned: the chaos gate's time-to-failover referee."""
        try:
            from ray_tpu._private import cluster_events as cev
            # price the failover in re-executed steps: everything past
            # the checkpoint the gang resumes from, up to the last step
            # any rank reported, runs again
            resume_step = None
            if self.resume_from_checkpoint is not None:
                try:
                    raw = self.resume_from_checkpoint.to_dict() \
                        .get("step")
                    resume_step = raw if isinstance(raw, int) else None
                except Exception:
                    resume_step = None
            lost = None
            if resume_step is not None and self._last_step is not None:
                lost = max(0, self._last_step - resume_step)
            elif self._last_step is not None and \
                    self.resume_from_checkpoint is None:
                lost = self._last_step + 1   # from-scratch restart
            cev.emit(
                cev.TRAIN_GANG_RECOVERY,
                f"gang for {name!r} re-formed (attempt {attempt}): "
                f"{self._last_failure[:200]}",
                severity="WARNING", experiment=name, attempt=attempt,
                reason=self._last_failure[:500],
                downtime_s=(round(time.monotonic() - t_failed, 3)
                            if t_failed else None),
                resumed_from_checkpoint=self.resume_from_checkpoint
                is not None, resume_step=resume_step,
                last_step=self._last_step, lost_steps=lost)
        except Exception:
            pass    # observability must never fail the loop

    def _poll_group(self, group: WorkerGroup):
        import ray_tpu
        done: List[Any] = [None] * len(group.workers)
        watch = _GangWatch(group)
        start_refs = list(getattr(group, "_start_refs", ()))
        while True:
            round_items: List[Any] = []
            try:
                if start_refs:
                    ready, start_refs = ray_tpu.wait(
                        start_refs, num_returns=len(start_refs), timeout=0)
                    try:
                        ray_tpu.get(ready)
                    except Exception as e:
                        raise TrainingFailedError(
                            f"start_training failed: {e}") from e
                for rank, w in enumerate(group.workers):
                    if done[rank] is not None:
                        continue
                    watch.check()
                    try:
                        item = ray_tpu.get(
                            w.next_result.remote(timeout=10.0),
                            timeout=120.0)
                    except Exception as e:
                        raise TrainingFailedError(
                            f"worker {rank} died: {e}") from e
                    if item[0] == "error":
                        raise TrainingFailedError(
                            f"train loop failed on worker {rank}:\n"
                            f"{item[1]}")
                    if item[0] == "done":
                        done[rank] = ("done", item[1])
                    elif item[0] == "result":
                        round_items.append((rank, item[1], item[2]))
            except TrainingFailedError:
                # a mid-round failure must not discard state the gang
                # already handed over: first the items consumed THIS
                # round, then a sweep of results reported but not yet
                # consumed (session.report parks the rank until
                # consumption) — during a graceful preemption the
                # draining ranks are still alive, and a dropped
                # checkpoint here is a whole checkpoint interval of
                # lost work
                for rank, metrics, ckpt in round_items:
                    if rank == 0:
                        yield metrics, ckpt
                yield from self._final_harvest(group, done)
                raise
            if all(d is not None for d in done):
                return
            for rank, metrics, ckpt in round_items:
                if rank == 0:
                    yield metrics, ckpt

    @staticmethod
    def _final_harvest(group: WorkerGroup, done: List[Any]):
        """Best-effort drain of pending rank reports on the failover
        path; yields rank-0 (metrics, checkpoint) pairs like the normal
        poll (dead ranks fail the RPC fast and are skipped)."""
        import ray_tpu
        for rank, w in enumerate(group.workers):
            if done[rank] is not None:
                continue
            for _ in range(8):   # bounded: this is a teardown path
                try:
                    item = ray_tpu.get(w.next_result.remote(timeout=0.1),
                                       timeout=15.0)
                except Exception:
                    break
                if item[0] != "result":
                    break
                if rank == 0:
                    yield item[1], item[2]

    def fit(self) -> Result:
        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        exp_dir = os.path.join(
            self.run_config.storage_path,
            self.run_config.name or f"{type(self).__name__}_"
                                    f"{time.strftime('%Y%m%d_%H%M%S')}")
        os.makedirs(exp_dir, exist_ok=True)
        last_metrics: Dict[str, Any] = {}
        kept: List[Any] = []   # (score, Checkpoint, metrics)
        error: Optional[Exception] = None
        try:
            for metrics, ckpt in self._iter_results():
                last_metrics = metrics
                if ckpt is not None:
                    kept.append((self._score(metrics, ckpt_cfg), ckpt,
                                 metrics))
                    kept = self._prune(kept, ckpt_cfg)
                if self._should_stop(metrics):
                    break
        except TrainingFailedError as e:
            error = e
        best = kept[-1][1] if kept else None
        if kept and ckpt_cfg.checkpoint_score_attribute:
            ordered = sorted(kept, key=lambda t: t[0],
                             reverse=ckpt_cfg.checkpoint_score_order == "max")
            best = ordered[0][1]
        # training failures come back on the Result (Tune-style); callers
        # that want an exception check result.error
        return Result(metrics=last_metrics, checkpoint=best, error=error,
                      log_dir=exp_dir,
                      best_checkpoints=[(c, m) for _, c, m in kept])

    def _score(self, metrics: Dict[str, Any], cfg: CheckpointConfig):
        attr = cfg.checkpoint_score_attribute
        if attr and attr in metrics:
            return metrics[attr]
        return metrics.get("training_iteration", 0)

    def _prune(self, kept: List[Any], cfg: CheckpointConfig) -> List[Any]:
        if cfg.num_to_keep is None or len(kept) <= cfg.num_to_keep:
            return kept
        if cfg.checkpoint_score_attribute:
            kept = sorted(kept, key=lambda t: t[0],
                          reverse=cfg.checkpoint_score_order == "max")
            return kept[:cfg.num_to_keep]
        return kept[-cfg.num_to_keep:]

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        for k, v in stop.items():
            if k in metrics and metrics[k] >= v:
                return True
        return False
