"""TorchTrainer: torch.distributed data-parallel training on CPU hosts.

Parity analog of /root/reference/python/ray/train/torch/torch_trainer.py +
config.py:29 (TCP rendezvous → init_process_group) +
train_loop_utils.py (prepare_model/prepare_data_loader). On this framework
torch is a CPU-side citizen (rollout preprocessing, GBDT-style workloads);
the TPU path is JaxTrainer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.base_trainer import (BackendConfig, DataParallelTrainer,
                                        WorkerGroup)


class TorchConfig(BackendConfig):
    def __init__(self, backend: str = "gloo", timeout_s: float = 120.0):
        self.backend = backend
        self.timeout_s = timeout_s

    def on_start(self, worker_group: WorkerGroup,
                 scaling: ScalingConfig) -> None:
        if scaling.num_workers <= 1:
            return
        ip = worker_group.execute_single(0, "get_node_ip")
        port = worker_group.execute_single(0, "find_free_port")
        # the process group itself is initialized lazily inside the loop by
        # prepare_model() → _maybe_init_process_group(), rendezvousing on
        # these env vars
        worker_group.execute("set_env", {
            "MASTER_ADDR": ip, "MASTER_PORT": str(port),
            "RAY_TPU_TORCH_BACKEND": self.backend,
            "RAY_TPU_TORCH_TIMEOUT_S": str(self.timeout_s)})

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        pass


def _maybe_init_process_group() -> None:
    import os
    from ray_tpu.air import session
    s = session.get_session()
    if s is None or s.world_size <= 1:
        return
    import datetime
    import torch.distributed as dist
    if dist.is_initialized():
        return
    dist.init_process_group(
        backend=os.environ.get("RAY_TPU_TORCH_BACKEND", "gloo"),
        rank=s.world_rank, world_size=s.world_size,
        timeout=datetime.timedelta(seconds=float(
            os.environ.get("RAY_TPU_TORCH_TIMEOUT_S", "120"))),
        init_method=f"tcp://{os.environ['MASTER_ADDR']}:"
                    f"{os.environ['MASTER_PORT']}")


def prepare_model(model):
    """Wrap an nn.Module in DDP when world_size > 1 (cf. reference
    train/torch/train_loop_utils.py prepare_model)."""
    from ray_tpu.air import session
    _maybe_init_process_group()
    s = session.get_session()
    if s is not None and s.world_size > 1:
        from torch.nn.parallel import DistributedDataParallel
        model = DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across workers with DistributedSampler."""
    from ray_tpu.air import session
    s = session.get_session()
    if s is None or s.world_size <= 1:
        return loader
    import torch.utils.data as tud
    sampler = tud.distributed.DistributedSampler(
        loader.dataset, num_replicas=s.world_size, rank=s.world_rank)
    return tud.DataLoader(loader.dataset, batch_size=loader.batch_size,
                          sampler=sampler, num_workers=0,
                          collate_fn=loader.collate_fn,
                          drop_last=loader.drop_last)


class TorchTrainer(DataParallelTrainer):
    backend_config_cls = TorchConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
