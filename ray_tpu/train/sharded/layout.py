"""GSPMD layout planner: ShardingConfig -> mesh + canonical PartitionSpecs.

The single mesh authority of the repo (docs/train_sharded.md).  A
:class:`ShardingConfig` names the parallelism degrees the way a user
thinks about them — dp / fsdp / cp / tp / pp — and :func:`plan` resolves
them against a device count into a :class:`LayoutPlan`: the mesh shape
(in :data:`ray_tpu.parallel.mesh.AXIS_ORDER`), the actual ``Mesh``, and
the canonical ``PartitionSpec`` table per parameter/activation class.

The spec table is *derived from* the same rule table
(:data:`ray_tpu.parallel.sharding.DEFAULT_RULES`) that
``make_sharded_train`` applies to the model's logical axis metadata, so
the planner's golden table and the shardings actually compiled into the
step cannot drift apart — the table is the contract, the rules are the
implementation.

``pp`` is the MPMD pipeline degree: pp>1 partitions *layers* onto stage
actors connected by compiled-DAG shm channels (pipeline.py), it is not a
mesh axis.  The SPMD GPipe 'stage' mesh axis
(parallel/pipeline.py spmd_pipeline) is requested with
``pp_style="spmd"`` instead, and ``slices>1`` pins the data axis across
a slice boundary (hierarchical DCN+ICI mesh).

This module also owns the per-train-loop mesh cache that used to live in
jax_trainer.py (``get_mesh`` / ``set_loop_mesh_shape`` re-export from
there for compatibility).
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.jax_compat import PartitionSpec
from ray_tpu.parallel.mesh import AXIS_ORDER
from ray_tpu.parallel.sharding import LOGICAL_RULES, MeshAxes, ShardingRules

_local = threading.local()

# parameter / activation classes -> the model's logical axes, the same
# names gpt.py hangs on params via nn.with_logical_partitioning.  The
# planner's table is these axes pushed through the rule table with
# size-1 mesh axes pruned — exactly what tree_mesh_shardings does to the
# abstract state in make_sharded_train.
PARAM_CLASSES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    ("token_embed", ("vocab", "embed")),
    ("attn_qkv", ("embed", "heads", "head_dim")),
    ("attn_kv", ("embed", "kv", "head_dim")),
    ("attn_out", ("heads_embed", "embed")),
    ("mlp_up", ("embed", "mlp")),
    ("mlp_down", ("mlp", "embed")),
    ("norm_scale", ("norm",)),
    ("lm_head", ("embed", "vocab")),
)
ACTIVATION_CLASSES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    ("batch_tokens", ("batch", None)),
    ("hidden", ("batch", "seq", "act_embed")),
    ("logits", ("batch", "seq", "act_vocab")),
)


@dataclasses.dataclass(frozen=True)
class ShardingConfig:
    """Parallelism degrees for one training run.

    ``dp``/``fsdp``/``cp``/``tp`` are in-mesh axes (data, fsdp, context,
    tensor in AXIS_ORDER); exactly one may be ``-1`` to absorb remaining
    devices.  ``pp`` is the pipeline degree — MPMD stage actors by
    default (``pp_style="mpmd"``: *layers* split onto actors, the mesh
    below describes one stage's devices), or the SPMD GPipe 'stage'
    mesh axis with ``pp_style="spmd"``.  ``slices>1`` builds the mesh
    from an explicit device grid with the data axis outermost across the
    slice boundary (hierarchical DCN/ICI layout, cf. the 2-slice
    MULTICHIP dryrun).
    """

    dp: int = 1
    fsdp: int = 1
    cp: int = 1
    tp: int = 1
    pp: int = 1
    pp_style: str = "mpmd"          # "mpmd" (stage actors) | "spmd" (mesh axis)
    slices: int = 1

    def __post_init__(self):
        if self.pp_style not in ("mpmd", "spmd"):
            raise ValueError(f"pp_style must be mpmd|spmd, "
                             f"got {self.pp_style!r}")
        sizes = [self.dp, self.fsdp, self.cp, self.tp]
        if self.pp_style == "spmd":
            sizes.append(self.pp)
        elif self.pp < 1:
            raise ValueError("mpmd pp degree must be >= 1")
        if sum(1 for s in sizes if s == -1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        for s in sizes:
            if s != -1 and s < 1:
                raise ValueError(f"axis sizes must be >= 1 or -1, got {s}")
        if self.slices < 1:
            raise ValueError("slices must be >= 1")

    def mesh_axes(self) -> Dict[str, int]:
        """Unresolved mesh axes in AXIS_ORDER (may still contain -1)."""
        shape = {"stage": self.pp if self.pp_style == "spmd" else 1,
                 "data": self.dp, "fsdp": self.fsdp,
                 "context": self.cp, "tensor": self.tp}
        assert tuple(shape) == AXIS_ORDER
        return shape

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Fill the -1 wildcard against ``n_devices`` (one stage's
        devices when pp_style="mpmd": callers pass devices-per-stage)."""
        shape = self.mesh_axes()
        names = list(shape)
        sizes = list(shape.values())
        wild = [i for i, v in enumerate(sizes) if v == -1]
        fixed = math.prod(v for v in sizes if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {dict(zip(names, sizes))} needs {fixed} devices, "
                f"have {n_devices}")
        return dict(zip(names, sizes))


def _prune_axes(axes: MeshAxes, shape: Dict[str, int]) -> MeshAxes:
    """ShardingRules._prune against a *shape dict* (no Mesh needed, so
    golden tables never touch the backend)."""
    if axes is None:
        return None
    if isinstance(axes, str):
        return axes if shape.get(axes, 1) > 1 else None
    kept = tuple(a for a in axes if shape.get(a, 1) > 1)
    return kept if len(kept) > 1 else (kept[0] if kept else None)


@dataclasses.dataclass(frozen=True)
class LayoutPlan:
    """A resolved layout: mesh shape + canonical spec tables + stage map."""

    config: ShardingConfig
    mesh_shape: Dict[str, int]       # resolved, AXIS_ORDER, per stage
    rules: ShardingRules = LOGICAL_RULES

    # -- spec tables ------------------------------------------------------
    def spec_for(self, logical_axes: Sequence[Optional[str]]
                 ) -> PartitionSpec:
        return PartitionSpec(*[
            _prune_axes(self.rules.to_mesh_axes(a), self.mesh_shape)
            if a is not None else None for a in logical_axes])

    def param_table(self) -> Dict[str, PartitionSpec]:
        return {name: self.spec_for(axes) for name, axes in PARAM_CLASSES}

    def activation_table(self) -> Dict[str, PartitionSpec]:
        return {name: self.spec_for(axes)
                for name, axes in ACTIVATION_CLASSES}

    # -- mesh authority ---------------------------------------------------
    def devices_per_stage(self, n_devices: Optional[int] = None) -> int:
        n = math.prod(self.mesh_shape.values())
        if n_devices is not None and n_devices != n * self.n_stages:
            raise ValueError(
                f"plan needs {n * self.n_stages} devices "
                f"({n}/stage x {self.n_stages} stages), have {n_devices}")
        return n

    def build_mesh(self, devices: Optional[Sequence[Any]] = None):
        """Build the (per-stage) jax Mesh.  ``slices>1`` reshapes an
        explicit grid so the slice boundary is pinned to the outermost
        non-trivial axis (data crosses DCN, fsdp/tensor stay on ICI)."""
        import jax
        import numpy as np
        from ray_tpu._private.jax_compat import Mesh
        from ray_tpu.parallel.mesh import MeshConfig, build_mesh

        shape = self.mesh_shape
        if devices is None:
            devices = jax.devices()[:math.prod(shape.values())]
        if self.config.slices > 1:
            names = [n for n in AXIS_ORDER if shape[n] > 1] or ["data"]
            if shape.get("data", 1) % self.config.slices:
                raise ValueError(
                    f"data axis {shape.get('data', 1)} not divisible by "
                    f"{self.config.slices} slices")
            grid = np.asarray(list(devices)).reshape(
                [shape[n] for n in names])
            return Mesh(grid, tuple(names))
        return build_mesh(
            MeshConfig(stage=shape["stage"], data=shape["data"],
                       fsdp=shape["fsdp"], context=shape["context"],
                       tensor=shape["tensor"]),
            devices=devices)

    # -- MPMD stage map ---------------------------------------------------
    @property
    def n_stages(self) -> int:
        return self.config.pp if self.config.pp_style == "mpmd" else 1

    def layer_ranges(self, n_layers: int) -> List[Tuple[int, int]]:
        """Contiguous [start, end) layer blocks per MPMD stage (remainder
        layers go to the *early* stages, which also carry the embed)."""
        stages = self.n_stages
        if n_layers < stages:
            raise ValueError(f"{n_layers} layers < {stages} stages")
        base, rem = divmod(n_layers, stages)
        ranges, start = [], 0
        for s in range(stages):
            end = start + base + (1 if s < rem else 0)
            ranges.append((start, end))
            start = end
        return ranges

    def describe(self) -> Dict[str, Any]:
        """JSON-able summary (bench rows, dryrun prints)."""
        return {
            "mesh": {k: v for k, v in self.mesh_shape.items() if v > 1},
            "pp": self.config.pp, "pp_style": self.config.pp_style,
            "slices": self.config.slices,
            "params": {k: str(v) for k, v in self.param_table().items()},
        }


def plan(config: ShardingConfig,
         n_devices: Optional[int] = None,
         rules: ShardingRules = LOGICAL_RULES) -> LayoutPlan:
    """Resolve ``config`` into a LayoutPlan.  ``n_devices`` is the
    per-stage device count (defaults to this process's
    ``jax.device_count()``, only touched when a wildcard or validation
    needs it)."""
    if n_devices is None:
        axes = config.mesh_axes()
        if any(v == -1 for v in axes.values()):
            import jax
            n_devices = jax.device_count()
        else:
            n_devices = math.prod(axes.values())
    return LayoutPlan(config=config, mesh_shape=config.resolve(n_devices),
                      rules=rules)


# ---------------------------------------------------------------------------
# The per-train-loop mesh cache (absorbed from jax_trainer.get_mesh /
# set_loop_mesh_shape: JaxTrainer installs the ScalingConfig's mesh_shape
# here and user loops call get_mesh()).
# ---------------------------------------------------------------------------

def _shape_to_config(mesh_shape: Dict[str, int]) -> ShardingConfig:
    """Arbitrary {axis: size} dict -> ShardingConfig.  Unknown axis names
    are rejected — AXIS_ORDER is the vocabulary of the mesh authority."""
    alias = {"data": "dp", "fsdp": "fsdp", "context": "cp",
             "tensor": "tp", "stage": "pp"}
    kw: Dict[str, Any] = {}
    for name, size in mesh_shape.items():
        if name not in alias:
            raise ValueError(
                f"unknown mesh axis {name!r}; expected one of "
                f"{list(alias)} (AXIS_ORDER)")
        kw[alias[name]] = size
    if "pp" in kw:
        kw["pp_style"] = "spmd"
    return ShardingConfig(**kw)


def get_mesh(mesh_shape: Optional[Dict[str, int]] = None):
    """Build (and cache, per train-loop thread) the device mesh.

    Inside a JaxTrainer loop, reads the mesh shape from the trainer's
    ScalingConfig when not given explicitly.  Axis sizes of -1 absorb
    remaining devices.  This is THE mesh constructor: jax_trainer,
    the sharded executor and the MULTICHIP dryruns all resolve through
    the same :func:`plan`.
    """
    import jax

    from ray_tpu._private.config import CONFIG

    if mesh_shape is None:
        mesh_shape = getattr(_local, "mesh_shape", None) or {}
    cached = getattr(_local, "mesh", None)
    if cached is not None and getattr(_local, "mesh_shape",
                                      None) == mesh_shape:
        return cached

    n = jax.device_count()
    if not mesh_shape:
        mesh_shape = dict(CONFIG.mesh_default_axes) or {"data": n}
    if sum(1 for v in mesh_shape.values() if v == -1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    p = plan(_shape_to_config(dict(mesh_shape)), n_devices=n)
    # preserve the caller's axis subset AND order: a {"data": 2,
    # "fsdp": 4} request yields a 2-axis mesh, not a 5-axis one — the
    # planner resolves/validates, the mesh is built over the requested
    # names only
    resolved = {k: p.mesh_shape[k] for k in mesh_shape}
    mesh = _build_named_mesh(resolved, jax.devices()[:n])
    _local.mesh = mesh
    _local.mesh_shape = resolved
    return mesh


def _build_named_mesh(shape: Dict[str, int], devices):
    from jax.experimental import mesh_utils

    from ray_tpu._private.jax_compat import Mesh
    names, sizes = list(shape), tuple(shape.values())
    try:
        dev_array = mesh_utils.create_device_mesh(
            sizes, devices=list(devices), allow_split_physical_axes=True)
    except (ValueError, AssertionError, NotImplementedError, TypeError):
        import numpy as np
        dev_array = np.asarray(list(devices)).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def set_loop_mesh_shape(shape: Optional[Dict[str, int]]) -> None:
    _local.mesh_shape = shape
    _local.mesh = None


# ---------------------------------------------------------------------------
# MULTICHIP dryrun configs (folded from __graft_entry__: the dryruns now
# consume planner layouts instead of hand-factoring devices).
# ---------------------------------------------------------------------------

def dryrun_plans(n_devices: int) -> List[Tuple[str, LayoutPlan]]:
    """The named layout sweep the MULTICHIP dryrun exercises:

      - ``train``: dp x fsdp x cp x tp greedy factorization (each model
        axis takes a 2 while divisible, data absorbs the rest),
      - ``pipeline_spmd``: 2-stage SPMD GPipe mesh (even device counts),
      - ``moe_ep``: expert-parallel layout (experts over data axes),
      - ``hier_2slice``: 2-slice hierarchical mesh, data across the
        slice boundary (multiples of 4).
    """
    sizes = {"tp": 1, "cp": 1, "fsdp": 1}
    rem = n_devices
    for axis in ("tp", "cp", "fsdp"):
        if rem % 2 == 0:
            sizes[axis] = 2
            rem //= 2
    out = [("train", plan(ShardingConfig(dp=rem, fsdp=sizes["fsdp"],
                                         cp=sizes["cp"], tp=sizes["tp"]),
                          n_devices=n_devices))]
    if n_devices % 2 == 0:
        out.append(("pipeline_spmd",
                    plan(ShardingConfig(dp=-1, pp=2, pp_style="spmd"),
                         n_devices=n_devices)))
        out.append(("moe_ep", plan(ShardingConfig(dp=-1, fsdp=2),
                                   n_devices=n_devices)))
    if n_devices % 4 == 0:
        per_slice = n_devices // 2
        out.append(("hier_2slice",
                    plan(ShardingConfig(dp=2, fsdp=2, tp=per_slice // 2,
                                        slices=2),
                         n_devices=n_devices)))
    return out
