"""Gang executor: the sharded-training flagship loop.

End-to-end wiring of the planes the repo has been building
(docs/train_sharded.md):

  - gang spawn through :class:`~ray_tpu.train.worker_group.WorkerGroup`
    + ``jax.distributed`` bootstrap (JaxConfig),
  - the layout planner's mesh/specs compiled into a SPLIT train step —
    ``grad_fn`` (jitted fwd+bwd) / host-plane
    ``sync_gradients(quantize="int8", async_op=True)`` / ``apply_fn``
    (jitted optimizer, donated state) — so cross-runtime data
    parallelism rides the DCN collective plane while fsdp/tp stay
    compiled into the step,
  - ICI-mesh registration with the PR 16 topology schedule when the
    gang shares one jax.distributed runtime,
  - sharded checkpoints through the object-transfer plane: each rank
    puts its leaf partition, refs land in the GCS KV, restore stripes
    the partitions back in and walks a fallback chain when shards died
    with a node.

Elasticity is inherited from DataParallelTrainer's gang recovery
(docs/fault_tolerance.md): a preempted node fails the incarnation, the
driver harvests the newest checkpoint and restarts the gang; lost work
is bounded by ``checkpoint_interval`` (+1 interval per checkpoint lost
to an ungraceful kill, see CONFIG.sharded_ckpt_keep).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.train.sharded.layout import ShardingConfig

_KV_PREFIX = "shardckpt"


# ---------------------------------------------------------------------------
# split grad/apply step
# ---------------------------------------------------------------------------

def make_grad_apply_step(model, mesh, optimizer=None, rules=None,
                         loss_fn=None, example_batch=None, z_loss=None):
    """Split variant of :func:`ray_tpu.train.step.make_sharded_train`.

    Returns ``(init_fn, grad_fn, apply_fn, state_shardings,
    batch_sharding)``:

      - ``grad_fn(state, batch) -> (grads, metrics)`` — jitted forward +
        backward, grads land in the params' shardings,
      - ``apply_fn(state, grads) -> state`` — jitted optimizer update
        with donated state.

    The split exists so a *host-plane* reduction can run between the
    two: ``sync_gradients`` sees materialized per-rank gradients, and
    with ``async_op=True`` the ring overlaps the host-side work between
    issue and fence.  The fused single-jit step stays the right call
    when the reduction is compiled into the graph instead.
    """
    import jax

    from ray_tpu.parallel.sharding import LOGICAL_RULES
    from ray_tpu.train.step import (OptimizerConfig, TrainState, lm_loss_fn,
                                    trace_state_shardings)
    optimizer = optimizer or OptimizerConfig()
    rules = rules or LOGICAL_RULES
    loss_fn = loss_fn or lm_loss_fn
    tx = optimizer.make()
    if z_loss is None:
        z_loss = getattr(getattr(model, "cfg", None), "z_loss", 0.0)

    def build_state(rng, batch) -> TrainState:
        variables = model.init(rng, batch["tokens"][:, :-1])
        return TrainState.create(apply_fn=model.apply,
                                 params=variables["params"], tx=tx)

    from ray_tpu._private.jax_compat import NamedSharding, PartitionSpec
    state_shardings, batch_sharding = trace_state_shardings(
        build_state, example_batch, mesh, rules, batch_axes=("batch", None))
    param_shardings = state_shardings.params
    repl = NamedSharding(mesh, PartitionSpec())

    def grad(state, batch):
        (loss, metrics), grads = jax.value_and_grad(
            lambda p: loss_fn(state.apply_fn, p, batch, z_loss),
            has_aux=True)(state.params)
        return grads, dict(metrics)

    def apply(state, grads):
        return state.apply_gradients(grads=grads)

    init_fn = jax.jit(build_state, out_shardings=state_shardings)
    grad_fn = jax.jit(grad,
                      in_shardings=(state_shardings, batch_sharding),
                      out_shardings=(param_shardings, repl))
    apply_fn = jax.jit(apply,
                       in_shardings=(state_shardings, param_shardings),
                       out_shardings=state_shardings,
                       donate_argnums=(0,))
    return init_fn, grad_fn, apply_fn, state_shardings, batch_sharding


# ---------------------------------------------------------------------------
# sharded checkpoints over the object-transfer plane
# ---------------------------------------------------------------------------

def _kv_key(tag: str, step: int, rank) -> str:
    return f"{_KV_PREFIX}/{tag}/{step}/{rank}"


def _gcs():
    from ray_tpu.runtime import core_worker as cw
    return cw.get_global_worker().gcs


def save_sharded_checkpoint(state, *, tag: str, step: int, rank: int,
                            world: int, keep_alive: List[Any]) -> None:
    """Put this rank's leaf partition and register the ref in the GCS KV.

    The state's flat leaves are partitioned round-robin across ranks
    (leaf i belongs to rank ``i % world``), so checkpoint bytes spread
    ~evenly over the gang's nodes and a restore stripes from every node
    at once.  ``keep_alive`` must outlive the checkpoint's usefulness:
    dropping the ref frees the shard (owner refcount).
    """
    import jax
    import numpy as np

    import ray_tpu

    leaves = jax.tree_util.tree_leaves(state)
    mine = {i: np.asarray(leaf) for i, leaf in enumerate(leaves)
            if i % world == rank}
    ref = ray_tpu.put({"step": step, "rank": rank, "leaves": mine})
    keep_alive.append(ref)
    from ray_tpu.runtime import core_worker as cw
    node = cw.get_global_worker().node_id
    _gcs().kv_put(_kv_key(tag, step, rank),
                  pickle.dumps({"ref": ref, "node": node,
                                "n_leaves": len(leaves)}))


def make_checkpoint_meta(*, tag: str, step: int, world: int,
                         chain: List[int]) -> Dict[str, Any]:
    """The rank-0 report checkpoint: no tensor bytes, just the KV
    coordinates plus the fallback chain of earlier checkpointed steps
    (newest first)."""
    return {"kind": "sharded_kv", "tag": tag, "step": step,
            "world": world, "chain": list(chain)}


class ShardRestoreError(RuntimeError):
    """Every checkpoint in the chain had at least one unrecoverable
    shard."""


def restore_sharded_checkpoint(meta: Dict[str, Any], state):
    """Rebuild ``state`` from a sharded checkpoint, walking the chain.

    Pulls every rank's partition (striped, multi-source: each shard
    lives on whichever node put or inherited it — the PR 5 pull engine
    and the PR 15 evacuation/orphan-fetch paths do the finding),
    reassembles the flat leaf list, and device_puts each leaf with the
    live state's sharding.  Returns ``(state, step)``; falls back one
    chain entry per missing shard set.
    """
    import jax

    import ray_tpu
    from ray_tpu._private.config import CONFIG

    tag, world = meta["tag"], meta["world"]
    treedef = jax.tree_util.tree_structure(state)
    shardings = [x.sharding for x in jax.tree_util.tree_leaves(state)]
    gcs = _gcs()
    errors = []
    for step in meta["chain"]:
        try:
            parts = []
            for rank in range(world):
                raw = gcs.kv_get(_kv_key(tag, step, rank))
                if raw is None:
                    raise ShardRestoreError(
                        f"step {step}: no KV entry for rank {rank}")
                parts.append(pickle.loads(raw))
            payloads = ray_tpu.get(
                [p["ref"] for p in parts],
                timeout=CONFIG.sharded_ckpt_pull_timeout_s)
            leaves_np: Dict[int, Any] = {}
            for payload in payloads:
                leaves_np.update(payload["leaves"])
            n = parts[0]["n_leaves"]
            if sorted(leaves_np) != list(range(n)):
                raise ShardRestoreError(
                    f"step {step}: leaf partitions incomplete "
                    f"({len(leaves_np)}/{n})")
            leaves = [jax.device_put(leaves_np[i], shardings[i])
                      for i in range(n)]
            return jax.tree_util.tree_unflatten(treedef, leaves), step
        except Exception as e:  # noqa: BLE001 — walk the chain
            errors.append(f"step {step}: {type(e).__name__}: {e}")
    raise ShardRestoreError(
        "no checkpoint in the chain was restorable: " + "; ".join(errors))


# ---------------------------------------------------------------------------
# ICI registration (PR 16 topology schedule)
# ---------------------------------------------------------------------------

def maybe_register_ici(mesh, *, axis: str = "data",
                       group_name: Optional[str] = None) -> bool:
    """Register the gang's mesh with the collective topology schedule
    when the contract holds: a multi-process jax runtime where every
    process holds exactly one local device on ``axis`` (then the
    intra-slice level of the hierarchical allreduce folds into one
    in-graph psum — docs/collective.md).  Returns whether registration
    happened; separate-runtime gangs (each worker its own device world)
    decline, their cross-worker reduction IS the host ring."""
    import jax

    from ray_tpu._private.config import CONFIG
    from ray_tpu.util import collective as col

    group_name = group_name or os.environ.get(
        "RAY_TPU_TRAIN_COLLECTIVE_GROUP", "")
    if not group_name or not col.is_group_initialized(group_name):
        return False
    if not CONFIG.collective_topology:
        return False
    if jax.process_count() <= 1 or mesh.shape.get(axis, 1) <= 1:
        return False
    # the in-graph reducer assembles a global array from ONE local
    # shard, so the contract is exactly one addressable device in the
    # mesh per process (collective.register_ici_mesh)
    local = [d for d in mesh.devices.flat
             if d.process_index == jax.process_index()]
    if len(local) != 1:
        return False
    col.register_ici_mesh(mesh, axis=axis, group_name=group_name)
    return True


# ---------------------------------------------------------------------------
# the canned sharded train loop + trainer
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardedRunConfig:
    """Everything the gang loop needs, picklable into train_loop_config."""

    sharding: ShardingConfig = dataclasses.field(
        default_factory=ShardingConfig)
    model: str = "tiny"
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    num_workers: int = 2
    steps: int = 8
    batch_per_worker: int = 4
    seq_len: int = 64
    checkpoint_interval: int = 2
    quantize: Optional[str] = "int8"
    async_grad_sync: bool = True
    register_ici: bool = True
    learning_rate: float = 1e-3
    optimizer: str = "adamw"
    seed: int = 0
    # slow-step throttle for chaos tests (seconds of host sleep per
    # step), so an injected preemption reliably lands mid-run
    step_sleep_s: float = 0.0
    # leave one GCS-KV breadcrumb per executed (rank, step, pid): the
    # chaos test and the bench's preemption leg count re-executed steps
    # exactly (lost work <= checkpoint_interval)
    kv_breadcrumbs: bool = False
    # per-worker peak FLOPs for the goodput ledger's MFU column
    # (0 = unknown: the ledger reports time buckets only)
    peak_flops: float = 0.0


def _synth_batch(cfg, vocab: int, rank: int, step: int):
    """Deterministic per-(rank, step) token batch: DP ranks see disjoint
    streams, a re-executed step sees identical data (exactly-once
    semantics for the chaos test's loss bookkeeping)."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(
        (cfg.seed * 1_000_003 + step) * 65_537 + rank)
    return {"tokens": jnp.asarray(
        rng.integers(0, vocab, (cfg.batch_per_worker, cfg.seq_len + 1)),
        jnp.int32)}


def sharded_train_loop(config: Dict[str, Any]):
    """The per-worker gang loop (module-level: workers import it)."""
    import jax
    import numpy as np

    from ray_tpu._private import step_stats
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.models import GPT, get_config
    from ray_tpu.train.jax_trainer import sync_gradients
    from ray_tpu.train.sharded import layout
    from ray_tpu.train.step import OptimizerConfig

    cfg: ShardedRunConfig = config["run"]
    rank = session.get_world_rank()
    world = session.get_world_size()
    tag = config.get("tag") or session.get_trial_id() or "sharded"

    plan = layout.plan(cfg.sharding, n_devices=jax.device_count()
                       if jax.process_count() == 1 else None)
    mesh = plan.build_mesh()
    model_cfg = get_config(cfg.model, **cfg.model_overrides)
    model = GPT(model_cfg, mesh=mesh)
    n_params = model_cfg.num_params()
    flops_per_token = (6 * n_params
                       + 12 * model_cfg.n_layers * model_cfg.d_model
                       * cfg.seq_len)
    step_stats.set_model_info(
        flops_per_token=flops_per_token,
        peak_flops=cfg.peak_flops or None,
        tokens_per_step=cfg.batch_per_worker * cfg.seq_len)

    batch = _synth_batch(cfg, model_cfg.vocab_size, rank, 0)
    opt = OptimizerConfig(learning_rate=cfg.learning_rate,
                          warmup_steps=1, decay_steps=max(10, cfg.steps),
                          optimizer=cfg.optimizer)
    init_fn, grad_fn, apply_fn, _, _ = make_grad_apply_step(
        model, mesh, opt, example_batch=batch)
    # same init seed on every DP rank: replicas must start identical,
    # divergence is what sync_gradients prevents
    state = init_fn(jax.random.PRNGKey(cfg.seed), batch)

    if cfg.register_ici:
        registered = maybe_register_ici(mesh)
    else:
        registered = False

    start_step = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        meta = ckpt.to_dict()
        if meta.get("kind") == "sharded_kv":
            state, start_step = restore_sharded_checkpoint(meta, state)
            start_step += 1

    clock = step_stats.step_clock()
    loss = float("nan")
    keep_alive: List[Any] = []
    chain: List[int] = list(
        (ckpt.to_dict().get("chain") if ckpt is not None else None) or [])
    from ray_tpu._private.config import CONFIG
    keep = max(1, int(CONFIG.sharded_ckpt_keep))

    for step in range(start_step, cfg.steps):
        if cfg.kv_breadcrumbs:
            _gcs().kv_put(f"shardsteps/{tag}/{rank}/{step}/{os.getpid()}",
                          b"1")
        clock.begin()
        with clock.phase("device_compute"):
            grads, metrics = grad_fn(
                state, _synth_batch(cfg, model_cfg.vocab_size, rank, step))
        if cfg.async_grad_sync:
            # issue the bucketed ring while the host prepares the next
            # batch (the overlap the PendingSync fence accounts for)
            pending = sync_gradients(grads, quantize=cfg.quantize,
                                     async_op=True)
            with clock.phase("host_dispatch"):
                next_batch = _synth_batch(cfg, model_cfg.vocab_size, rank,
                                          step + 1)
                del next_batch  # prefetch: generation cost is the point
            grads = pending.wait()
        else:
            grads = sync_gradients(grads, quantize=cfg.quantize)
        with clock.phase("optimizer"):
            state = apply_fn(state, grads)
        if cfg.step_sleep_s:
            import time
            time.sleep(cfg.step_sleep_s)
        loss = float(metrics["loss"])
        clock.end()
        out = {"step": step, "loss": loss, "rank": rank,
               "ici_registered": registered}
        report_ckpt = None
        if (step + 1) % cfg.checkpoint_interval == 0 \
                or step == cfg.steps - 1:
            save_sharded_checkpoint(state, tag=tag, step=step, rank=rank,
                                    world=world, keep_alive=keep_alive)
            chain.insert(0, step)
            del chain[keep:]
            del keep_alive[:-keep]
            if rank == 0:
                report_ckpt = Checkpoint.from_dict(make_checkpoint_meta(
                    tag=tag, step=step, world=world, chain=chain))
        session.report(out, checkpoint=report_ckpt)
    return {"final_loss": loss, "steps": cfg.steps,
            "ici_registered": registered}


class ShardedTrainer:
    """Driver-side front end: a DataParallelTrainer running
    :func:`sharded_train_loop` under a JaxConfig, with the planner's
    config threaded through.  ``fit()`` returns the underlying trainer's
    Result (gang recovery included)."""

    def __init__(self, run: ShardedRunConfig, *,
                 run_config=None, jax_config=None,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 tag: Optional[str] = None,
                 resume_from_checkpoint=None):
        from ray_tpu.air.config import ScalingConfig
        from ray_tpu.train.base_trainer import DataParallelTrainer
        from ray_tpu.train.jax_trainer import JaxConfig

        self.run = run
        scaling = ScalingConfig(num_workers=run.num_workers,
                                resources_per_worker=resources_per_worker)
        self._trainer = DataParallelTrainer(
            sharded_train_loop,
            train_loop_config={"run": run, "tag": tag},
            backend_config=jax_config or JaxConfig(init_distributed=False,
                                                   platform="cpu"),
            scaling_config=scaling,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint)

    def fit(self):
        return self._trainer.fit()
