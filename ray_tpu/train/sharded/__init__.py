"""ray_tpu.train.sharded: the sharded-training executor subsystem.

Three layers (docs/train_sharded.md):

  - :mod:`~ray_tpu.train.sharded.layout` — the GSPMD layout planner and
    the repo's single mesh authority: ``ShardingConfig`` (dp/fsdp/cp/tp/pp
    degrees) -> mesh + canonical ``PartitionSpec`` table per
    parameter/activation class.
  - :mod:`~ray_tpu.train.sharded.executor` — the gang executor:
    WorkerGroup spawn, jax.distributed bootstrap, ICI-mesh registration
    with the topology schedule, backward-overlapped int8 gradient sync,
    sharded checkpoints through the object-transfer plane.
  - :mod:`~ray_tpu.train.sharded.pipeline` — the MPMD pipeline runner:
    pp>1 stage actors compiled into one CompiledDAG over shm channels
    (zero per-microbatch task submission, 1F1B schedule).
"""

from ray_tpu.train.sharded.layout import (LayoutPlan,  # noqa: F401
                                          ShardingConfig, dryrun_plans,
                                          get_mesh, plan,
                                          set_loop_mesh_shape)
from ray_tpu.train.sharded.executor import (ShardedRunConfig,  # noqa: F401
                                            ShardedTrainer,
                                            make_grad_apply_step)
from ray_tpu.train.sharded.pipeline import (PipelineSpec,  # noqa: F401
                                            PipelineRunner, gpt_stage_specs)

__all__ = [
    "ShardingConfig", "LayoutPlan", "plan", "get_mesh",
    "set_loop_mesh_shape", "dryrun_plans",
    "ShardedTrainer", "ShardedRunConfig", "make_grad_apply_step",
    "PipelineRunner", "PipelineSpec", "gpt_stage_specs",
]
