"""MPMD pipeline runner: pp stage actors compiled into one DAG over shm
channels (docs/train_sharded.md, docs/compiled_dag.md).

The pp > 1 ``pp_style="mpmd"`` execution path of the sharded subsystem:
each pipeline stage is a long-lived actor owning its contiguous block of
transformer layers (plus the embedding on stage 0 and the head on the
last stage).  The whole 1F1B microbatch schedule is ONE compiled DAG —

    inp -> s0.forward -> ... -> sL.forward_loss_backward
        -> s(L-1).backward -> ... -> s0.backward

— an acyclic chain in which every non-final actor appears twice (its
forward op and its backward op).  Compiling with ``threaded_ops=True``
gives each op its own resident channel loop, so stage i runs forward of
microbatch t+1 while its backward op still waits on the cotangent of
microbatch t: the 1F1B interleave, with ``max_inflight`` bounding the
in-flight window to the pipeline depth.

Per microbatch the driver pays one ``execute()`` (a single shm channel
write) and one ``get()`` — ZERO classic task submissions, which
``PipelineRunner.run_step`` asserts through the owner's
``ray_tpu_actor_tasks_submitted_total`` counter.  Only the once-per-step
optimizer application goes through a classic actor call.

Backward is recompute-based (remat semantics): a stage stashes each
microbatch's INPUT, not vjp residuals, and its backward op re-runs the
forward under ``jax.grad`` of <output, cotangent>.  That keeps both
directions jittable (``jax.vjp``'s closure is not) and the stash O(input)
instead of O(activations).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu.dag.dag_node import InputNode
from ray_tpu.models.configs import TransformerConfig, get_config

_SUBMIT_METRIC = "ray_tpu_actor_tasks_submitted_total"


def _actor_submit_count() -> Optional[float]:
    """Owner-process total of classic actor-task submissions, or None
    when runtime metrics are disabled (the zero-submission assert then
    degrades to unchecked)."""
    snap = rtm.snapshot().get(_SUBMIT_METRIC)
    if not snap:
        return None
    return float(sum((snap.get("values") or {}).values()))


# --------------------------------------------------------------- stage split
@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One pipeline stage: a contiguous [lo, hi) block of layers, plus
    the embedding (first) / final-norm + head (last) bookends."""

    index: int
    n_stages: int
    lo: int
    hi: int

    @property
    def first(self) -> bool:
        return self.index == 0

    @property
    def last(self) -> bool:
        return self.index == self.n_stages - 1

    @property
    def n_layers(self) -> int:
        return self.hi - self.lo


def gpt_stage_specs(cfg: TransformerConfig, pp: int) -> List[StageSpec]:
    """Split a GPT config into ``pp`` contiguous stages (remainder layers
    go to the EARLY stages, matching ``LayoutPlan.layer_ranges``)."""
    if pp < 1:
        raise ValueError(f"pp must be >= 1, got {pp}")
    if pp > cfg.n_layers:
        raise ValueError(
            f"cannot split {cfg.n_layers} layers into {pp} pipeline stages")
    if pp > 1 and cfg.tie_embeddings:
        raise ValueError(
            "tie_embeddings puts the output head's weights on stage 0; "
            "untie them (tie_embeddings=False) to pipeline with pp > 1")
    base, rem = divmod(cfg.n_layers, pp)
    specs, lo = [], 0
    for i in range(pp):
        hi = lo + base + (1 if i < rem else 0)
        specs.append(StageSpec(index=i, n_stages=pp, lo=lo, hi=hi))
        lo = hi
    return specs


def split_params_by_stage(params: Any, specs: Sequence[StageSpec]) -> list:
    """Slice one full-model GPT param tree (scan-layers layout: block
    params stacked on axis 0 under ``blocks``) into per-stage trees whose
    scopes match ``_StageModule`` — the numerics-test bridge between a
    single-process reference model and the pipeline."""
    import flax.linen as nn
    import jax

    params = nn.meta.unbox(params)
    if "blocks" not in params:
        raise ValueError(
            "split_params_by_stage needs the scan-layers param layout "
            "(cfg.scan_layers=True): expected a stacked 'blocks' scope, "
            f"got {sorted(params)}")
    out = []
    for st in specs:
        p: Dict[str, Any] = {}
        if st.first:
            p["embed"] = params["embed"]
        if st.n_layers:
            p["blocks"] = jax.tree.map(lambda a, st=st: a[st.lo:st.hi],
                                       params["blocks"])
        if st.last:
            p["final_norm"] = params["final_norm"]
            p["lm_head"] = params["lm_head"]
        out.append(p)
    return out


def lm_loss(logits, targets):
    """Mean next-token cross entropy — shared by the last stage and the
    single-process reference the numerics test compares against."""
    import jax
    import jax.numpy as jnp

    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


# --------------------------------------------------------------- stage model
def _stage_module(cfg: TransformerConfig, spec: StageSpec):
    """Flax module for one stage, with param scopes that are a SUBSET of
    the full GPT tree ('embed', 'blocks', 'final_norm', 'lm_head') so a
    full-model checkpoint splits cleanly (split_params_by_stage)."""
    import flax.linen as nn
    import jax.numpy as jnp

    from ray_tpu.models.gpt import RMSNorm, Block, _dense, stack_layers
    from ray_tpu.ops.layers import rope_frequencies

    class _StageModule(nn.Module):
        cfg: TransformerConfig = dataclasses.field(default_factory=lambda: cfg)

        @nn.compact
        def __call__(self, x):
            c = self.cfg
            if spec.first:
                embed = self.param(
                    "embed",
                    nn.with_logical_partitioning(
                        nn.initializers.normal(stddev=0.02),
                        ("vocab", "embed")),
                    (c.vocab_size, c.d_model), c.param_dtype)
                x = jnp.take(embed, x, axis=0).astype(c.dtype)
            else:
                x = x.astype(c.dtype)
            if spec.n_layers:
                cos, sin = rope_frequencies(c.head_dim, c.max_seq_len,
                                            c.rope_theta)
                x = stack_layers(Block, c, {}, x, (cos, sin, None, None),
                                 remat=False, n_layers=spec.n_layers)
            if not spec.last:
                return x
            x = RMSNorm(c.norm_eps, name="final_norm")(x)
            logits = _dense(c.vocab_size, ("embed", "vocab"), "lm_head",
                            dtype=c.dtype, param_dtype=c.param_dtype)(x)
            return logits.astype(jnp.float32)

    return _StageModule()


@ray_tpu.remote
class PipelineStageActor:
    """One MPMD stage: owns its param slice + grad accumulator, exposes
    the compiled-DAG ops (forward / forward_loss_backward / backward) and
    the classic once-per-step ``apply_grads``.

    Channel payloads are dicts of numpy arrays; ``targets`` ride the
    forward chain so only the driver's InputNode carries batch data."""

    def __init__(self, cfg: TransformerConfig, spec: StageSpec, *,
                 lr: float = 1e-2, seed: int = 0, params=None):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self.spec = spec
        self.lr = float(lr)
        self.module = _stage_module(cfg, spec)
        if params is None:
            shape = ((1, 8) if spec.first
                     else (1, 8, cfg.d_model))
            dummy = (jnp.zeros(shape, jnp.int32) if spec.first
                     else jnp.zeros(shape, cfg.dtype))
            params = self.module.init(
                jax.random.PRNGKey(seed * 1009 + spec.index), dummy)["params"]
        self.params = jax.tree.map(jnp.asarray, nn.meta.unbox(params))

        apply = self.module.apply
        self._fwd = jax.jit(lambda p, x: apply({"params": p}, x))
        if spec.last:
            def _loss(p, x, tgt):
                return lm_loss(apply({"params": p}, x), tgt)
            # argnums=(0, 1): one fused pass yields the stage's param
            # grads AND the cotangent handed upstream
            self._loss_grad = jax.jit(
                jax.value_and_grad(_loss, argnums=(0, 1)))
        else:
            def _dot(p, x, d):
                out = apply({"params": p}, x)
                return jnp.vdot(out.astype(jnp.float32),
                                d.astype(jnp.float32))
            # grad of <f(p, x), d> == VJP with cotangent d; recompute-
            # based so backward stays a single jittable function
            argnums = (0,) if spec.first else (0, 1)
            self._bwd = jax.jit(jax.grad(_dot, argnums=argnums))
        self._apply = jax.jit(
            lambda p, g, n: jax.tree.map(
                lambda pp, gg: (pp - self.lr * gg / n).astype(pp.dtype),
                p, g),
            donate_argnums=(0,))
        self._stash: collections.deque = collections.deque()
        self._acc = None
        self._n_acc = 0

    # ------------------------------------------------------ compiled-DAG ops
    def forward(self, payload: dict) -> dict:
        import numpy as np
        x = payload["tokens"] if self.spec.first else payload["acts"]
        self._stash.append(x)
        acts = self._fwd(self.params, x)
        return {"acts": np.asarray(acts), "targets": payload["targets"]}

    def forward_loss_backward(self, payload: dict) -> dict:
        import numpy as np
        x = payload["acts"]
        (loss, (d_p, d_x)) = self._loss_grad(self.params, x,
                                             payload["targets"])
        self._accumulate(d_p)
        return {"d_acts": np.asarray(d_x), "loss": float(loss)}

    def backward(self, payload: dict):
        import numpy as np
        x = self._stash.popleft()
        grads = self._bwd(self.params, x, payload["d_acts"])
        self._accumulate(grads[0])
        if self.spec.first:
            return payload["loss"]
        return {"d_acts": np.asarray(grads[1]), "loss": payload["loss"]}

    # ------------------------------------------------------- classic methods
    def _accumulate(self, g) -> None:
        import jax
        self._acc = g if self._acc is None else jax.tree.map(
            lambda a, b: a + b, self._acc, g)
        self._n_acc += 1

    def apply_grads(self) -> int:
        """Once-per-step optimizer: SGD over the microbatch-mean grads.
        (The full optimizer/precision stack lives in the executor path;
        the pipeline runner's contract is the schedule, not the tx.)"""
        if self._n_acc == 0:
            return 0
        if self._stash:
            raise RuntimeError(
                f"stage {self.spec.index}: {len(self._stash)} forward "
                "stashes not consumed by backward — apply_grads called "
                "mid-step?")
        n = self._n_acc
        self.params = self._apply(self.params, self._acc, float(n))
        self._acc, self._n_acc = None, 0
        return n

    def reset_grads(self) -> int:
        """Drop the accumulated grads WITHOUT updating params (numerics
        probes that only want the forward losses)."""
        n = self._n_acc
        self._acc, self._n_acc = None, 0
        self._stash.clear()
        return n

    def ready(self) -> int:
        """Creation fence: the DAG compiler requires live actors."""
        return self.spec.index

    def get_params(self):
        import numpy as np
        import jax
        return jax.tree.map(np.asarray, self.params)


# --------------------------------------------------------------------- spec
@dataclasses.dataclass
class PipelineSpec:
    """A pipelined training run (pp MPMD stages, 1F1B over one compiled
    DAG).  ``microbatches`` per step; each microbatch is
    [microbatch_size, seq_len] tokens."""

    model: str = "tiny"
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    pp: int = 2
    microbatches: int = 4
    microbatch_size: int = 2
    seq_len: int = 32
    steps: int = 4
    lr: float = 1e-2
    seed: int = 0
    max_inflight: Optional[int] = None      # None -> pp (the 1F1B window)
    buffer_bytes: Optional[int] = None      # None -> sized from shapes
    threaded_ops: bool = True               # False: serial per-actor loop

    def config(self) -> TransformerConfig:
        return get_config(self.model, **self.model_overrides)


def synth_microbatches(spec: PipelineSpec, cfg: TransformerConfig,
                       step: int) -> List[dict]:
    """Deterministic synthetic token microbatches (same convention as the
    executor's ``_synth_batch``: seed x step keyed, rank-free here)."""
    out = []
    for m in range(spec.microbatches):
        rng = np.random.default_rng(
            (spec.seed * 1_000_003 + step) * 65_537 + m)
        toks = rng.integers(0, cfg.vocab_size,
                            (spec.microbatch_size, spec.seq_len + 1),
                            dtype=np.int32)
        out.append({"tokens": toks[:, :-1], "targets": toks[:, 1:]})
    return out


# -------------------------------------------------------------------- runner
class PipelineRunner:
    """Driver handle: spawns the stage actors, compiles the DAG once, and
    pumps microbatches through it.

    ``stage_params`` (optional) injects per-stage param trees — the
    numerics test splits one full-model init via
    ``split_params_by_stage`` so the pipeline and the single-process
    reference start bit-identical."""

    def __init__(self, spec: PipelineSpec, *,
                 stage_params: Optional[Sequence[Any]] = None):
        self.spec = spec
        self.cfg = spec.config()
        self.stages = gpt_stage_specs(self.cfg, spec.pp)
        if stage_params is not None and len(stage_params) != spec.pp:
            raise ValueError(
                f"stage_params has {len(stage_params)} entries for "
                f"pp={spec.pp}")
        self.actors = [
            PipelineStageActor.remote(
                self.cfg, st, lr=spec.lr, seed=spec.seed,
                params=None if stage_params is None else stage_params[i])
            for i, st in enumerate(self.stages)]
        # actor creation is async and the DAG compiler rejects non-live
        # actors (it resolves channel endpoints at compile time): fence
        # on a trivial call — also absorbs each stage's jax/flax import
        ray_tpu.get([a.ready.remote() for a in self.actors], timeout=600.0)
        self._dag = self._compile()
        self.telemetry: Dict[str, Any] = {
            "executes": 0,
            "classic_submits_hot_loop": 0.0 if _actor_submit_count()
            is not None else None,
        }

    def _compile(self):
        spec, cfg = self.spec, self.cfg
        with InputNode() as inp:
            node = inp
            for a in self.actors[:-1]:
                node = a.forward.bind(node)
            node = self.actors[-1].forward_loss_backward.bind(node)
            for a in reversed(self.actors[:-1]):
                node = a.backward.bind(node)
        if spec.buffer_bytes is not None:
            buf = spec.buffer_bytes
        else:
            # largest payload on any edge: fp32 activations (or logits'
            # cotangent) + targets + pickle framing slack
            acts = 4 * spec.microbatch_size * spec.seq_len * cfg.d_model
            buf = max(1 << 16, 2 * acts + 8 * spec.microbatch_size
                      * spec.seq_len + 4096)
        return node.experimental_compile(
            max_inflight=spec.max_inflight or spec.pp,
            buffer_size_bytes=buf, threaded_ops=spec.threaded_ops,
            name=f"pp{spec.pp}-{spec.model}")

    def run_step(self, microbatches: Optional[List[dict]] = None, *,
                 step: int = 0, timeout: float = 120.0) -> Dict[str, Any]:
        """One optimizer step: pump every microbatch through the compiled
        chain (zero classic submissions — asserted), then one classic
        ``apply_grads`` per stage."""
        if microbatches is None:
            microbatches = synth_microbatches(self.spec, self.cfg, step)
        c0 = _actor_submit_count()
        refs = [self._dag.execute(mb) for mb in microbatches]
        losses = [r.get(timeout=timeout) for r in refs]
        c1 = _actor_submit_count()
        if c0 is not None and c1 is not None:
            delta = c1 - c0
            self.telemetry["classic_submits_hot_loop"] += delta
            if delta:
                raise RuntimeError(
                    f"compiled pipeline hot loop issued {delta} classic "
                    "task submissions; the zero-submission contract is "
                    "broken (docs/compiled_dag.md)")
        self.telemetry["executes"] += len(microbatches)
        applied = ray_tpu.get(
            [a.apply_grads.remote() for a in self.actors], timeout=timeout)
        assert all(n == len(microbatches) for n in applied), applied
        return {"loss": float(np.mean(losses)),
                "losses": [float(x) for x in losses],
                "microbatches": len(microbatches)}

    def train(self, steps: Optional[int] = None) -> Dict[str, Any]:
        steps = self.spec.steps if steps is None else steps
        history = [self.run_step(step=s)["loss"] for s in range(steps)]
        n_exec = max(1, self.telemetry["executes"])
        subs = self.telemetry["classic_submits_hot_loop"]
        return {
            "steps": steps,
            "loss_history": history,
            "final_loss": history[-1] if history else float("nan"),
            "executes": self.telemetry["executes"],
            "classic_submits_hot_loop": subs,
            "submissions_per_microbatch":
                None if subs is None else subs / n_exec,
        }

    def forward_loss(self, microbatches: List[dict],
                     timeout: float = 120.0) -> List[float]:
        """Losses WITHOUT an optimizer step (numerics comparisons): runs
        the full fwd+bwd chain, then discards the accumulated grads."""
        refs = [self._dag.execute(mb) for mb in microbatches]
        losses = [float(r.get(timeout=timeout)) for r in refs]
        ray_tpu.get([a.reset_grads.remote() for a in self.actors],
                    timeout=timeout)
        return losses

    def stage_params(self) -> list:
        return ray_tpu.get([a.get_params.remote() for a in self.actors])

    def shutdown(self) -> None:
        try:
            self._dag.teardown()
        except Exception:
            pass
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
