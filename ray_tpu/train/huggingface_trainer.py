"""HuggingFaceTrainer: run a transformers.Trainer inside Train workers.

Analog of /root/reference/python/ray/train/huggingface/
huggingface_trainer.py: the user supplies
``trainer_init_per_worker(train_dataset, eval_dataset, **config) ->
transformers.Trainer``; each Train worker builds it against its dataset
shard, a TrainerCallback forwards every transformers log to
``session.report`` (with a checkpoint at save events), and the standard
Train result/checkpoint plumbing applies. CPU torch here (this image);
the TPU-native path is JaxTrainer — this wrapper exists for drop-in
parity with HF training code.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.torch_trainer import TorchConfig, TorchTrainer


def _make_loop(trainer_init_per_worker: Callable):
    def train_loop(config: Dict[str, Any]):
        import transformers
        from ray_tpu.air import session
        from ray_tpu.air.checkpoint import Checkpoint

        train_ds = session.get_dataset_shard("train")
        eval_ds = session.get_dataset_shard("evaluation")
        if train_ds is not None and hasattr(train_ds, "to_torch"):
            train_ds = train_ds.to_torch()
        if eval_ds is not None and hasattr(eval_ds, "to_torch"):
            eval_ds = eval_ds.to_torch()
        trainer: "transformers.Trainer" = trainer_init_per_worker(
            train_ds, eval_ds, **(config or {}))

        class _ReportCallback(transformers.TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                if not logs:
                    return
                metrics = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                metrics["step"] = state.global_step
                metrics["epoch"] = float(state.epoch or 0.0)
                session.report(metrics)

            def on_save(self, args, state, control, **kwargs):
                import os
                ckpt_dir = os.path.join(
                    args.output_dir,
                    f"checkpoint-{state.global_step}")
                if os.path.isdir(ckpt_dir):
                    session.report(
                        {"step": state.global_step, "saved": True},
                        checkpoint=Checkpoint.from_directory(ckpt_dir))

        trainer.add_callback(_ReportCallback())
        resume_dir = None
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            # resume transformers' own optimizer/scheduler/step state
            resume_dir = ckpt.to_directory()
        result = trainer.train(resume_from_checkpoint=resume_dir)
        final = {k: v for k, v in (result.metrics or {}).items()
                 if isinstance(v, (int, float))}
        final["done"] = True
        session.report(final)

    return train_loop


class HuggingFaceTrainer(TorchTrainer):
    """``HuggingFaceTrainer(trainer_init_per_worker, scaling_config=...,
    datasets={"train": ds}).fit()`` (cf. reference
    huggingface_trainer.py)."""

    def __init__(self, trainer_init_per_worker: Callable, *,
                 trainer_init_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(
            _make_loop(trainer_init_per_worker),
            train_loop_config=trainer_init_config,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
