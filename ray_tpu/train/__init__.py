"""ray_tpu.train: trainers over worker-group actors + the pjit step library.

Analog of /root/reference/python/ray/train (SURVEY.md §2.4): BaseTrainer /
DataParallelTrainer drive a WorkerGroup; JaxTrainer is the TPU flagship
(mesh + GSPMD shardings instead of DDP); TorchTrainer keeps CPU-torch
parity; ray_tpu.train.step holds the sharded train-step builder.
"""

from ray_tpu.train.base_trainer import (BackendConfig,  # noqa: F401
                                        BaseTrainer, DataParallelTrainer,
                                        TrainingFailedError)
from ray_tpu.train.huggingface_trainer import \
    HuggingFaceTrainer  # noqa: F401
from ray_tpu.train.jax_trainer import (JaxConfig, JaxTrainer,  # noqa: F401
                                       PendingSync, get_mesh,
                                       sync_gradients)
from ray_tpu.train.gbdt_trainer import (GBDTTrainer,  # noqa: F401
                                        LightGBMTrainer, SklearnPredictor,
                                        XGBoostTrainer)
from ray_tpu.train.predictor import (BatchPredictor,  # noqa: F401
                                     JaxPredictor, Predictor)
from ray_tpu.train.step import (OptimizerConfig,  # noqa: F401
                                classification_loss_fn, lm_loss_chunked_fn,
                                lm_loss_fn, make_sharded_train,
                                make_vision_train)
from ray_tpu.train.torch_trainer import (TorchConfig,  # noqa: F401
                                         TorchTrainer, prepare_data_loader,
                                         prepare_model)
from ray_tpu.train.worker_group import TrainWorker, WorkerGroup  # noqa: F401
# the sharded-training subsystem (docs/train_sharded.md): GSPMD layout
# planner + gang executor + MPMD pipeline over compiled-DAG channels
from ray_tpu.train.sharded import (LayoutPlan,  # noqa: F401
                                   PipelineRunner, PipelineSpec,
                                   ShardedRunConfig, ShardedTrainer,
                                   ShardingConfig)
# training performance plane (docs/observability.md): the per-step
# phase clock + goodput ledger a train loop drives
from ray_tpu._private.step_stats import (instrument_step,  # noqa: F401
                                         set_model_info, step_clock)

__all__ = [
    "BaseTrainer", "DataParallelTrainer", "BackendConfig",
    "TrainingFailedError", "JaxTrainer", "JaxConfig", "get_mesh",
    "sync_gradients", "PendingSync", "step_clock", "instrument_step",
    "set_model_info",
    "TorchTrainer", "TorchConfig", "prepare_model", "prepare_data_loader",
    "WorkerGroup", "TrainWorker", "make_sharded_train", "OptimizerConfig",
    "make_vision_train", "classification_loss_fn", "Predictor",
    "JaxPredictor", "BatchPredictor", "GBDTTrainer", "XGBoostTrainer",
    "LightGBMTrainer", "SklearnPredictor",
    "lm_loss_fn", "lm_loss_chunked_fn", "HuggingFaceTrainer",
    "ShardingConfig", "LayoutPlan", "ShardedRunConfig", "ShardedTrainer",
    "PipelineSpec", "PipelineRunner",
]
