"""JobSupervisor actor + JobSubmissionClient SDK.

Cite: /root/reference/python/ray/dashboard/modules/job/job_manager.py
(JobManager.submit_job :431 -> JobSupervisor actor :133 runs the driver as
a subprocess) and python/ray/job_submission/sdk.py. Differences: state
lives in the GCS KV (the reference also persists JobInfo in the GCS KV);
log tailing returns the KV-buffered output instead of a REST stream.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

_KV_PREFIX = "job_submission:"
_LOG_PREFIX = "job_logs:"
_STOP_PREFIX = "job_stop:"
_MAX_LOG_BYTES = 4 * 1024 * 1024


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Optional[dict] = None
    driver_exit_code: Optional[int] = None


def _kv():
    from ray_tpu.runtime.core_worker import get_global_worker
    return get_global_worker().gcs


def _save(info: JobInfo) -> None:
    _kv().kv_put(_KV_PREFIX + info.submission_id,
                 json.dumps(asdict(info)).encode())


def _load(submission_id: str) -> Optional[JobInfo]:
    raw = _kv().kv_get(_KV_PREFIX + submission_id)
    return JobInfo(**json.loads(raw)) if raw else None


class JobSupervisor:
    """Detached actor that shepherds one job's driver subprocess.

    Runs on any cluster node; holds zero CPUs so it never competes with
    the job's own tasks (reference JobSupervisor does the same).
    """

    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: Dict[str, str],
                 runtime_env: Optional[dict] = None):
        self.info = JobInfo(submission_id=submission_id,
                            entrypoint=entrypoint, metadata=metadata,
                            runtime_env=runtime_env)
        _save(self.info)

    def ping(self) -> bool:
        return True

    def run(self) -> str:
        from ray_tpu.runtime.core_worker import get_global_worker
        worker = get_global_worker()
        gcs_host, gcs_port = worker.gcs._conn._sock.getpeername()

        self.info.status = JobStatus.RUNNING
        self.info.start_time = time.time()
        _save(self.info)

        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = f"{gcs_host}:{gcs_port}"
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self.info.submission_id
        if self.info.runtime_env and self.info.runtime_env.get("env_vars"):
            env.update(self.info.runtime_env["env_vars"])

        proc = subprocess.Popen(
            self.info.entrypoint, shell=True, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, bufsize=1)
        buf: List[str] = []
        buf_bytes = 0
        lock = threading.Lock()

        def _pump():
            nonlocal buf_bytes
            for line in proc.stdout:
                with lock:
                    buf.append(line)
                    buf_bytes += len(line)
                    while buf_bytes > _MAX_LOG_BYTES and len(buf) > 1:
                        buf_bytes -= len(buf.pop(0))

        pump = threading.Thread(target=_pump, daemon=True)
        pump.start()

        stopped = False
        while proc.poll() is None:
            if _kv().kv_get(_STOP_PREFIX + self.info.submission_id):
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                stopped = True
                break
            with lock:
                _kv().kv_put(_LOG_PREFIX + self.info.submission_id,
                             "".join(buf).encode())
            time.sleep(0.5)
        pump.join(timeout=5)
        with lock:
            _kv().kv_put(_LOG_PREFIX + self.info.submission_id,
                         "".join(buf).encode())

        code = proc.returncode
        self.info.driver_exit_code = code
        self.info.end_time = time.time()
        if stopped:
            self.info.status = JobStatus.STOPPED
            self.info.message = "stopped by user"
        elif code == 0:
            self.info.status = JobStatus.SUCCEEDED
        else:
            self.info.status = JobStatus.FAILED
            self.info.message = f"driver exited with code {code}"
        _save(self.info)
        return self.info.status


class JobSubmissionClient:
    """SDK + CLI backend. `address` is the GCS host:port (or None to use
    the already-initialized driver / the latest local session)."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            if address is None:
                address = os.environ.get("RAY_TPU_ADDRESS") or \
                    latest_session_address()
            ray_tpu.init(address=address)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   runtime_env: Optional[dict] = None) -> str:
        submission_id = submission_id or \
            "raysubmit_" + uuid.uuid4().hex[:16]
        if _load(submission_id) is not None:
            raise ValueError(f"job {submission_id} already exists")
        supervisor = ray_tpu.remote(JobSupervisor).options(
            num_cpus=0, name=f"_job_supervisor:{submission_id}",
            lifetime="detached").remote(
                submission_id, entrypoint, metadata or {}, runtime_env)
        ray_tpu.get(supervisor.ping.remote())  # surface creation errors
        supervisor.run.remote()  # fire and forget
        self._hold_supervisor(submission_id, supervisor)
        return submission_id

    # keep handles so the driver doesn't GC the fire-and-forget result ref
    _held: Dict[str, Any] = {}

    def _hold_supervisor(self, sid: str, handle) -> None:
        JobSubmissionClient._held[sid] = handle

    def get_job_info(self, submission_id: str) -> JobInfo:
        info = _load(submission_id)
        if info is None:
            raise ValueError(f"job {submission_id} not found")
        return info

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id).status

    def get_job_logs(self, submission_id: str) -> str:
        raw = _kv().kv_get(_LOG_PREFIX + submission_id)
        return raw.decode("utf-8", "replace") if raw else ""

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in _kv().kv_keys(_KV_PREFIX):
            raw = _kv().kv_get(key)
            if raw:
                out.append(JobInfo(**json.loads(raw)))
        return sorted(out, key=lambda i: i.start_time)

    def stop_job(self, submission_id: str) -> bool:
        info = _load(submission_id)
        if info is None or info.status in JobStatus.TERMINAL:
            return False
        _kv().kv_put(_STOP_PREFIX + submission_id, b"1")
        return True

    def delete_job(self, submission_id: str) -> bool:
        info = _load(submission_id)
        if info is None:
            return False
        if info.status not in JobStatus.TERMINAL:
            raise RuntimeError("stop the job before deleting it")
        _kv().kv_del(_KV_PREFIX + submission_id)
        _kv().kv_del(_LOG_PREFIX + submission_id)
        _kv().kv_del(_STOP_PREFIX + submission_id)
        return True

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.5)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")


def latest_session_address() -> str:
    """GCS address of the most recent local session (see node.py)."""
    path = "/tmp/ray_tpu_sessions/latest.json"
    try:
        with open(path) as f:
            info = json.load(f)
        return f"{info['gcs_host']}:{info['gcs_port']}"
    except (OSError, ValueError, KeyError):
        raise ConnectionError(
            "no running cluster found: pass address=, set RAY_TPU_ADDRESS, "
            "or start one with `python -m ray_tpu.scripts start --head`")
