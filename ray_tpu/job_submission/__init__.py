"""Job submission: run driver scripts on the cluster, track their lifecycle.

Analog of /root/reference/python/ray/job_submission/ (JobSubmissionClient,
JobStatus) + dashboard/modules/job/job_manager.py (JobManager :431,
JobSupervisor :133): a detached zero-CPU supervisor actor runs the
entrypoint as a subprocess on a cluster node, streams its output into the
GCS KV, and records status transitions there.
"""

from ray_tpu.job_submission.job_manager import (  # noqa: F401
    JobInfo, JobStatus, JobSubmissionClient)

__all__ = ["JobSubmissionClient", "JobStatus", "JobInfo"]
