"""Preallocated single-writer/multi-reader shm channels for compiled DAGs.

A ``Channel`` is ONE object in the node's ``SharedMemoryStore`` segment,
created once at compile time and then mutated in place: a fixed ring of
``nslots`` payload slots plus a small header and one 8-byte ack counter
per reader.  Every process on the node maps the same segment, so a
write is a memcpy into shared memory and a read is a poll on the slot's
sequence word — **zero per-item allocation, zero RPCs, zero task
submissions** (docs/compiled_dag.md).  This is the transport the
reference's accelerated/compiled DAGs build on plasma-backed
IntraProcessChannel/shm channels; here the ring lives directly on the
store segment from runtime/object_store.py.

Layout (little endian, offsets from the start of the channel object)::

    0   u32  magic
    4   u32  layout version
    8   u32  nslots
    12  u32  nreaders
    16  u64  per-slot payload capacity
    24  u64  poison code (0 = live)
    32  ...  reserved to 64
    64  u64  acks[nreaders]   -- acks[r] = items reader r consumed
    ..  slots: [u64 seq | u64 len | u64 flags | payload] * nslots

Protocol (seqlock-flavored, no cross-process atomics needed):

* the single writer publishes item ``k`` into slot ``k % nslots`` by
  writing payload, then ``len``/``flags``, then ``seq = k + 1`` LAST;
  it may only do so once ``min(acks) > k - nslots`` (every reader has
  released the slot's previous tenant) — that wait IS the ring's
  backpressure.
* reader ``r`` waits for ``slot.seq == k + 1``, copies the payload out,
  then publishes ``acks[r] = k + 1``.  Each ack word has exactly one
  writer, so no counter is ever contended.

Correctness leans on x86-TSO store ordering (stores become visible in
program order) and on 8-byte aligned copies being effectively atomic —
the same assumptions every shm seqlock makes; aarch64 would need
barriers this pure-Python layer cannot express, so compiled DAGs are
gated to the x86 hosts this repo targets.

Error propagation: ``flags`` bit 0 marks the payload as a serialized
exception (written via ``serialize(err, error_type=...)``), so
``deserialize`` on the consumer raises it — a failed stage forwards the
raw error payload downstream and the driver's ``get()`` re-raises.

Poisoning: any participant may stamp the header's poison word; every
blocked wait polls it and unwinds with ``ChannelClosedError``, which is
how worker death and ``teardown()`` wake the whole graph.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import List, Optional, Tuple

from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private import serialization as ser
from ray_tpu._private.analysis import channel_check
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ChannelClosedError, ChannelTimeoutError

_MAGIC = 0x52435448          # "RCTH"
_LAYOUT_VERSION = 1
_HEADER_BYTES = 64
_SLOT_HEADER = 24            # u64 seq | u64 len | u64 flags
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# payload flag bits
FLAG_ERROR = 1               # payload is a serialized exception

# poison codes
POISON_TEARDOWN = 1
POISON_WORKER_DIED = 2

# wait loop: a short yield-spin keeps the hot pipelined case off the
# sleep quantum entirely, then a two-tier exponential backoff — short
# sleeps (<= 0.5 ms) while the wait is young so an active graph's stage
# handoffs stay sub-millisecond, escalating to 5 ms polls once a wait
# has been parked past _PARK_AFTER_S so resident actor loops idling
# between executions cost ~0.1% CPU instead of ~1%
_SPIN_YIELDS = 256
_SLEEP_MIN_S = 0.00005
_SLEEP_MAX_S = 0.0005
_SLEEP_PARKED_S = 0.005
_PARK_AFTER_S = 0.05
_EVERY_POISON_CHECK = 8      # poll poison every N sleeps, not every spin

# channel-path telemetry (docs/compiled_dag.md / docs/observability.md)
_M_WRITE_WAIT = rtm.histogram(
    "ray_tpu_dag_channel_write_wait_ms",
    "time a compiled-DAG channel writer blocked on ring credit")
_M_READ_WAIT = rtm.histogram(
    "ray_tpu_dag_channel_read_wait_ms",
    "time a compiled-DAG channel reader blocked for the next item")


def channel_object_id(seed: bytes) -> ObjectID:
    """Deterministic 20-byte store id for a channel (compile stamps the
    DAG id + role into ``seed`` so driver and actors derive the same)."""
    import hashlib
    return ObjectID(hashlib.sha1(b"dagchan:" + seed).digest()[:20])


def channel_size(nslots: int, nreaders: int, capacity: int) -> int:
    return _HEADER_BYTES + 8 * nreaders + nslots * (_SLOT_HEADER + capacity)


class Channel:
    """Attached view over one channel object (see module docstring).

    The instance holds the store pin for the mapped object; ``close()``
    releases it.  One process may attach the same channel once and share
    the instance between its writer/readers — attach is idempotent at
    the compiled-DAG layer, not here.
    """

    def __init__(self, store, oid: ObjectID, view: memoryview):
        self._store = store
        self.oid = oid
        self._view = view
        magic, version = _U32.unpack_from(view, 0)[0], _U32.unpack_from(view, 4)[0]
        if magic != _MAGIC:
            raise ChannelClosedError(
                f"object {oid.hex()[:12]} is not a channel (bad magic)")
        if version != _LAYOUT_VERSION:
            raise ChannelClosedError(
                f"channel {oid.hex()[:12]} layout v{version} != "
                f"v{_LAYOUT_VERSION}")
        self.nslots = _U32.unpack_from(view, 8)[0]
        self.nreaders = _U32.unpack_from(view, 12)[0]
        self.capacity = _U64.unpack_from(view, 16)[0]
        self._acks_off = _HEADER_BYTES
        self._slots_off = self._acks_off + 8 * self.nreaders
        self._slot_stride = _SLOT_HEADER + self.capacity
        self._closed = False
        self._close_lock = threading.Lock()
        # yield-spin budget before the sleep backoff: compiled DAGs
        # keep the aggressive default (latency-critical, usually more
        # cores than spinners); participants with MANY channels per
        # core (collective rings, docs/collective.md) turn it down —
        # N ranks yield-spinning on fewer cores starve the one rank
        # that has real work, inverting the latency win
        self.spin_yields = _SPIN_YIELDS
        # protocol sanitizer gate, resolved per attach so suites can
        # flip RAY_TPU_DEBUG_CHANNELS without reimporting this module
        self._debug = channel_check.enabled()

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def create(cls, store, oid: ObjectID, *, nslots: int, nreaders: int,
               capacity: int) -> "Channel":
        """Allocate + seal the channel object and return an attached
        (pinned) view.  Only the compiling driver calls this."""
        if nslots < 1 or nreaders < 1 or capacity < 1:
            raise ValueError("nslots, nreaders and capacity must be >= 1")
        # the slot stride is _SLOT_HEADER + capacity: round capacity up
        # so every slot's u64 seq/len/flags words stay 8-byte aligned —
        # the protocol's effectively-atomic-store assumption does not
        # hold for a misaligned word
        capacity = (capacity + 7) & ~7
        total = channel_size(nslots, nreaders, capacity)
        buf = store.create(oid, total, meta=0, allow_evict=True)
        try:
            # zero the control words (segment memory may be recycled);
            # payload areas don't need it
            buf[:_HEADER_BYTES + 8 * nreaders] = \
                bytes(_HEADER_BYTES + 8 * nreaders)
            _U32.pack_into(buf, 0, _MAGIC)
            _U32.pack_into(buf, 4, _LAYOUT_VERSION)
            _U32.pack_into(buf, 8, nslots)
            _U32.pack_into(buf, 12, nreaders)
            _U64.pack_into(buf, 16, capacity)
            stride = _SLOT_HEADER + capacity
            base = _HEADER_BYTES + 8 * nreaders
            for i in range(nslots):
                buf[base + i * stride:base + i * stride + _SLOT_HEADER] = \
                    bytes(_SLOT_HEADER)
        except BaseException:
            buf.release()
            store.abort(oid)
            raise
        buf.release()
        store.seal(oid)
        return cls.attach(store, oid, timeout=5.0)

    @classmethod
    def attach(cls, store, oid: ObjectID,
               timeout: Optional[float] = 10.0) -> "Channel":
        """Map an existing channel; pins it until ``close()``.  Raises
        ChannelTimeoutError when the object never appears — on a
        compiled DAG that means the actor lives on a different node
        than the driver's segment (docs/compiled_dag.md limits)."""
        res = store.get(oid, timeout=timeout)
        if res is None:
            raise ChannelTimeoutError(
                f"channel object {oid.hex()[:12]} not present in the local "
                f"shared-memory segment (compiled DAGs require all "
                f"participants on the driver's node)")
        view, _meta = res
        try:
            return cls(store, oid, view)
        except BaseException:
            view.release()
            store.release(oid)
            raise

    def close(self) -> None:
        """Release this process's pin (idempotent)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._view.release()
        try:
            self._store.release(self.oid)
        except Exception:
            pass

    def delete(self) -> bool:
        """Best-effort removal of the backing object (driver teardown,
        after every participant released its pin)."""
        try:
            return self._store.delete(self.oid)
        except Exception:
            return False

    # ------------------------------------------------------------ poisoning
    def poison(self, code: int = POISON_TEARDOWN) -> None:
        _U64.pack_into(self._view, 24, code)

    def poison_code(self) -> int:
        return _U64.unpack_from(self._view, 24)[0]

    # ------------------------------------------------------------ internals
    def _slot_off(self, k: int) -> int:
        return self._slots_off + (k % self.nslots) * self._slot_stride

    def _min_acks(self) -> int:
        v = self._view
        off = self._acks_off
        lo = _U64.unpack_from(v, off)[0]
        for r in range(1, self.nreaders):
            a = _U64.unpack_from(v, off + 8 * r)[0]
            if a < lo:
                lo = a
        return lo

    def _wait(self, ready, deadline: Optional[float],
              stop: Optional[threading.Event], what: str) -> None:
        """Poll ``ready()`` with yield-spin then backoff; raises on
        poison / stop / timeout.  Shared by reader and writer."""
        for _ in range(self.spin_yields):
            if ready():
                return
            time.sleep(0)
        delay = _SLEEP_MIN_S
        ticks = 0
        start = time.monotonic()
        while True:
            if ready():
                return
            ticks += 1
            if ticks % _EVERY_POISON_CHECK == 0 or delay >= _SLEEP_MAX_S:
                code = self.poison_code()
                if code:
                    raise ChannelClosedError(
                        f"channel {self.oid.hex()[:12]} poisoned "
                        f"(code={code}) while waiting to {what}")
                if stop is not None and stop.is_set():
                    raise ChannelClosedError(
                        f"channel {self.oid.hex()[:12]}: local stop while "
                        f"waiting to {what}")
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                raise ChannelTimeoutError(
                    f"timed out waiting to {what} on channel "
                    f"{self.oid.hex()[:12]}")
            time.sleep(delay)
            cap = (_SLEEP_MAX_S if now - start < _PARK_AFTER_S
                   else _SLEEP_PARKED_S)
            delay = min(delay * 2, cap)


class ChannelWriter:
    """The channel's single writer; tracks its own publish cursor."""

    def __init__(self, channel: Channel):
        self.channel = channel
        self.seq = 0                   # items published so far
        # debug-mode writer identity: claims the ring's header claim
        # word on first publish so a second writer instance trips the
        # single-writer check (analysis/channel_check.py)
        self._wid = channel_check.writer_id() if channel._debug else 0

    def writable(self) -> bool:
        """True when the ring has a free slot, i.e. the next write will
        not block on ring credit.  Callers that must never block (the
        collective segment outbox, docs/collective.md) poll this and
        queue locally instead."""
        return self.channel._min_acks() > self.seq - self.channel.nslots

    def write_payload(self, head: bytes, views: List[memoryview],
                      flags: int = 0, timeout: Optional[float] = None,
                      stop: Optional[threading.Event] = None) -> None:
        """Publish one serialized item ((head, out-of-band views) as
        produced by ``serialization.serialize``) directly into the ring
        slot — no intermediate flat-bytes copy."""
        ch = self.channel
        size = ser.serialized_size(head, views)
        if size > ch.capacity:
            raise ValueError(
                f"serialized item ({size} B) exceeds the channel's "
                f"per-slot capacity ({ch.capacity} B); recompile with a "
                f"larger buffer_size_bytes")
        k = self.seq
        floor = k - ch.nslots          # min acks needed to reuse the slot
        if ch._min_acks() <= floor:    # fast-path check before stamping t0
            t0 = rtm.now()
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            ch._wait(lambda: ch._min_acks() > floor, deadline, stop,
                     "write")
            _M_WRITE_WAIT.observe_since(t0)
        if ch._debug:
            channel_check.check_publish(ch, k, self._wid)
        off = ch._slot_off(k)
        payload = ch._view[off + _SLOT_HEADER:off + _SLOT_HEADER + size]
        try:
            ser.write_into(payload, head, views)
        finally:
            payload.release()
        _U64.pack_into(ch._view, off + 8, size)
        _U64.pack_into(ch._view, off + 16, flags)
        # seq is published LAST (x86-TSO keeps the payload stores ahead)
        _U64.pack_into(ch._view, off, k + 1)
        self.seq = k + 1

    def write_raw(self, payload: bytes, flags: int,
                  timeout: Optional[float] = None,
                  stop: Optional[threading.Event] = None) -> None:
        """Publish pre-serialized bytes (error forwarding path)."""
        self.write_payload(payload, [], flags=flags, timeout=timeout,
                           stop=stop)

    def write(self, value, timeout: Optional[float] = None,
              stop: Optional[threading.Event] = None) -> None:
        head, views = ser.serialize(value)
        self.write_payload(head, views, flags=0, timeout=timeout, stop=stop)

    def write_error(self, error: BaseException,
                    timeout: Optional[float] = None,
                    stop: Optional[threading.Event] = None) -> None:
        head, views = ser.serialize(error, error_type=ser.ERROR_TASK)
        self.write_payload(head, views, flags=FLAG_ERROR, timeout=timeout,
                           stop=stop)


class ChannelReader:
    """One registered reader (``idx`` is its compile-assigned ack slot);
    tracks its own consume cursor."""

    def __init__(self, channel: Channel, idx: int):
        if not 0 <= idx < channel.nreaders:
            raise ValueError(f"reader index {idx} out of range "
                             f"(nreaders={channel.nreaders})")
        self.channel = channel
        self.idx = idx
        self.seq = 0                   # items consumed so far

    def read_zc(self, timeout: Optional[float] = None,
                stop: Optional[threading.Event] = None):
        """Zero-copy blocking read: returns ``(payload_view, flags,
        ack)``.  The view maps the ring slot DIRECTLY — consume it
        (deserialize / reduce / copy out), then call ``ack()`` exactly
        once to release the slot; the view is invalid afterwards.  Acks
        must fire in read order (each ack publishes its own cumulative
        counter, so acking item k+1 before k would release k's slot
        early).  The collective shm transport reduces straight out of
        the ring through this (docs/collective.md)."""
        ch = self.channel
        k = self.seq
        off = ch._slot_off(k)
        view = ch._view
        want = k + 1
        if _U64.unpack_from(view, off)[0] != want:
            t0 = rtm.now()
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            ch._wait(lambda: _U64.unpack_from(view, off)[0] == want,
                     deadline, stop, "read")
            _M_READ_WAIT.observe_since(t0)
        size = _U64.unpack_from(view, off + 8)[0]
        flags = _U64.unpack_from(view, off + 16)[0]
        if ch._debug:
            channel_check.check_read(ch, k, size)
        payload = view[off + _SLOT_HEADER:off + _SLOT_HEADER + size]

        def ack(_view=view, _ch=ch, _idx=self.idx, _want=want):
            if _ch._debug:
                channel_check.check_ack(_ch, _idx, _want)
            try:
                _U64.pack_into(_view, _ch._acks_off + 8 * _idx, _want)
            except ValueError:
                pass  # channel closed underneath a late ack

        self.seq = want
        return payload, flags, ack

    def read_raw(self, timeout: Optional[float] = None,
                 stop: Optional[threading.Event] = None
                 ) -> Tuple[bytes, int]:
        """Blocking next item as (payload bytes, flags).  The payload is
        copied out of the ring before acking, so the returned bytes stay
        valid across slot reuse."""
        view, flags, ack = self.read_zc(timeout=timeout, stop=stop)
        payload = bytes(view)
        # ack AFTER the copy: the writer may reuse the slot immediately
        ack()
        return payload, flags

    def read(self, timeout: Optional[float] = None,
             stop: Optional[threading.Event] = None):
        """Blocking next value; raises the carried exception for error
        items (their serialized payload re-raises on deserialize)."""
        payload, _flags = self.read_raw(timeout=timeout, stop=stop)
        return ser.deserialize(payload)
