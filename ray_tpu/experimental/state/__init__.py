"""Cluster state introspection: list/summarize tasks, actors, objects, ...

Analog of /root/reference/python/ray/experimental/state/ (api.py,
state_manager.py) + dashboard/state_aggregator.py:132 (StateAPIManager).
"""

from ray_tpu.experimental.state.api import (  # noqa: F401
    collect_debug_bundle, doctor_report, doctor_report_text,
    get_dossier, list_actors, list_cluster_events, list_dossiers,
    list_jobs, list_metrics, list_metrics_history, list_nodes,
    list_objects, list_placement_groups, list_recovery_episodes,
    list_step_stats, list_tasks, list_traces,
    list_workers, get_trace, memory_summary, metrics_history_stats,
    metrics_summary, recovery_stats,
    summarize_actors, summarize_objects, summarize_tasks, timeline,
    trace_stats, trace_timeline, trace_tree_text, training_summary,
    training_summary_text)

__all__ = [
    "list_tasks", "list_actors", "list_nodes", "list_jobs", "list_objects",
    "list_workers", "list_placement_groups", "list_metrics",
    "list_cluster_events", "get_dossier", "list_dossiers",
    "list_step_stats", "training_summary", "training_summary_text",
    "summarize_tasks", "summarize_actors", "summarize_objects",
    "memory_summary", "metrics_summary", "timeline",
    "list_traces", "get_trace", "trace_stats", "trace_timeline",
    "trace_tree_text",
    "list_metrics_history", "metrics_history_stats",
    "list_recovery_episodes", "recovery_stats",
    "doctor_report", "doctor_report_text", "collect_debug_bundle",
]
