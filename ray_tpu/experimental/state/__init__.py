"""Cluster state introspection: list/summarize tasks, actors, objects, ...

Analog of /root/reference/python/ray/experimental/state/ (api.py,
state_manager.py) + dashboard/state_aggregator.py:132 (StateAPIManager).
"""

from ray_tpu.experimental.state.api import (  # noqa: F401
    get_dossier, list_actors, list_cluster_events, list_dossiers,
    list_jobs, list_metrics, list_nodes, list_objects,
    list_placement_groups, list_step_stats, list_tasks, list_traces,
    list_workers, get_trace, memory_summary, metrics_summary,
    summarize_actors, summarize_objects, summarize_tasks, timeline,
    trace_stats, trace_timeline, trace_tree_text, training_summary,
    training_summary_text)

__all__ = [
    "list_tasks", "list_actors", "list_nodes", "list_jobs", "list_objects",
    "list_workers", "list_placement_groups", "list_metrics",
    "list_cluster_events", "get_dossier", "list_dossiers",
    "list_step_stats", "training_summary", "training_summary_text",
    "summarize_tasks", "summarize_actors", "summarize_objects",
    "memory_summary", "metrics_summary", "timeline",
    "list_traces", "get_trace", "trace_stats", "trace_timeline",
    "trace_tree_text",
]
