"""State API: `list_*` / `summarize_*` / `memory_summary` / `timeline`.

Analog of /root/reference/python/ray/experimental/state/api.py (list_tasks
etc.), state_cli.py (`ray list tasks`), _private/state.py:829 (`ray
timeline` Chrome-trace export) and `ray memory` (refcount debugging).

Data sources: the GCS tables (tasks/actors/nodes/jobs/placement groups) and
live fan-out to raylets (`list_workers`) and core workers
(`core_worker_stats`) for objects — mirroring the reference's
StateDataSourceClient (state_manager.py:130).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu.runtime import core_worker as cw


def _gcs():
    return cw.get_global_worker().gcs


# --------------------------------------------------------------- GCS tables
def list_tasks(*, job_id: Optional[str] = None, state: Optional[str] = None,
               name: Optional[str] = None, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_task_events", {
        "job_id": job_id, "state": state, "name": name, "limit": limit})


def list_actors(*, state: Optional[str] = None,
                limit: int = 10000) -> List[dict]:
    actors = _gcs().call("list_actors")
    if state:
        actors = [a for a in actors if a.get("state") == state]
    return actors[:limit]


def list_nodes(*, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_nodes")[:limit]


def list_jobs(*, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_jobs")[:limit]


def list_placement_groups(*, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_placement_groups")[:limit]


# ----------------------------------------------------------- event plane
def list_cluster_events(*, node_id: Optional[str] = None,
                        job_id: Optional[str] = None,
                        actor_id: Optional[str] = None,
                        worker_id: Optional[str] = None,
                        severity: Optional[str] = None,
                        min_severity: Optional[str] = None,
                        type: Optional[str] = None,  # noqa: A002
                        source: Optional[str] = None,
                        limit: int = 1000) -> List[dict]:
    """Typed lifecycle events from the GCS cluster event table
    (docs/observability.md): node up/down/unhealthy, worker
    spawn/exit, actor restarts, lease timeouts, spill traffic,
    transfer failovers, collective rank deaths, serve replica
    retire/autoscale.  Id filters are prefix matches; ``severity`` is
    exact, ``min_severity`` a floor (DEBUG < INFO < WARNING < ERROR)."""
    return _gcs().call("list_cluster_events", {
        "node_id": node_id, "job_id": job_id, "actor_id": actor_id,
        "worker_id": worker_id, "severity": severity,
        "min_severity": min_severity, "type": type, "source": source,
        "limit": limit})


# ------------------------------------------------ training perf plane
def list_step_stats(run: Optional[str] = None, *, limit: int = 100,
                    steps_limit: int = 64) -> dict:
    """The GCS training step table (docs/observability.md): run
    directory rows (group, world, per-rank metadata, recent cross-rank
    skew) and — with ``run`` given (id or group prefix) — that run's
    per-step per-rank phase records."""
    return _gcs().call("list_step_stats", {
        "run": run, "limit": limit, "steps_limit": steps_limit})


def training_summary(run: Optional[str] = None) -> Optional[dict]:
    """The goodput-ledger view of one training run (latest by
    default): per-rank init/compile/productive/checkpoint/idle time
    buckets, tokens, MFU and goodput fraction, plus a cross-rank
    aggregate (docs/observability.md)."""
    return _gcs().call("training_summary", {"run": run})


def training_summary_text(run: Optional[str] = None) -> str:
    """Operator table for ``ray-tpu summary training``."""
    s = training_summary(run)
    if not s:
        return "(no training runs reported yet)"
    lines = [f"run {s['run']}"
             + (f"  (group {s['group']})" if s.get("group") else "")
             + f"  world={s['world']}  steps={s.get('steps_seen', 0)}"]
    agg = s.get("aggregate")
    if agg:
        lines.append(
            "aggregate: goodput %.1f%%  mfu %.2f%%  %s tokens  "
            "%.0f tokens/s" % (
                100 * agg.get("goodput", 0.0), 100 * agg.get("mfu", 0.0),
                f"{agg.get('tokens', 0):,}",
                agg.get("tokens_per_s", 0.0)))
    ranks = s.get("ranks") or {}
    if ranks:
        lines.append("%-5s %8s %9s %9s %11s %9s %9s %8s %7s" % (
            "RANK", "STEPS", "INIT(ms)", "COMP(ms)", "PROD(ms)",
            "CKPT(ms)", "IDLE(ms)", "GOODPUT", "MFU"))
        for rank, led in sorted(ranks.items(), key=lambda kv: int(kv[0])):
            lines.append("%-5s %8d %9.0f %9.0f %11.0f %9.0f %9.0f "
                         "%7.1f%% %6.2f%%" % (
                             rank, led.get("steps", 0),
                             led.get("init_ms", 0.0),
                             led.get("compile_ms", 0.0),
                             led.get("productive_ms", 0.0),
                             led.get("checkpoint_ms", 0.0),
                             led.get("idle_ms", 0.0),
                             100 * led.get("goodput", 0.0),
                             100 * led.get("mfu", 0.0)))
        # per-phase breakdown off rank 0 (the canonical series)
        led0 = ranks.get(0) or ranks.get("0") or \
            next(iter(ranks.values()))
        phases = led0.get("phase_ms") or {}
        if phases:
            lines.append("rank-0 phase totals: " + "  ".join(
                f"{k}={v:.0f}ms" for k, v in sorted(phases.items())))
    skew = s.get("skew") or []
    if skew:
        worst = max(skew, key=lambda r: r.get("skew_ms", 0.0))
        lines.append(
            "cross-rank skew (last %d analyzed steps): worst +%.1fms "
            "at step %d (median %.1fms)" % (
                len(skew), worst.get("skew_ms", 0.0),
                worst.get("step", 0), worst.get("median_ms", 0.0)))
    return "\n".join(lines)


# ----------------------------------------------------------- tracing plane
def list_traces(*, slo_violations: bool = False,
                route: Optional[str] = None,
                status: Optional[str] = None,
                since: Optional[float] = None,
                limit: int = 100) -> List[dict]:
    """Trace directory rows from the GCS span table
    (docs/observability.md): one row per retained trace — root
    name/route/pool, duration, TTFT/TPOT, SLO verdict, span count,
    dossier cross-link.  ``slo_violations=True`` narrows to requests
    that missed a target; ``route`` is a prefix match."""
    return _gcs().call("list_traces", {
        "slo_violations": slo_violations, "route": route,
        "status": status, "since": since, "limit": limit})


def get_trace(trace_id: str) -> Optional[dict]:
    """One full trace by id (prefix ok): every retained span sorted by
    start time, plus the root's SLO fields."""
    return _gcs().call("get_trace", {"trace_id": trace_id})


def trace_stats() -> dict:
    return _gcs().call("trace_stats", {})


def trace_tree_text(trace: dict) -> str:
    """Render one trace as an indented span tree (``ray-tpu trace``):
    parent/child structure, per-span duration/status, the hop
    decomposition of the request."""
    if not trace:
        return "(no such trace)"
    spans = trace.get("spans") or []
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s.get("span_id") for s in spans}
    for s in spans:
        parent = s.get("parent_id")
        if parent not in ids:
            parent = None     # orphan (parent rotated out): show at root
        by_parent.setdefault(parent, []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s.get("start", 0))
    lines = [f"trace {trace.get('trace_id', '?')}  "
             f"({len(spans)} spans"
             + (", truncated" if trace.get("truncated") else "") + ")"]
    root = trace.get("root") or {}
    if root.get("slo_ok") is not None:
        verdict = "OK" if root["slo_ok"] else (
            "VIOLATED " + ",".join(root.get("slo_violated") or []))
        lines.append(
            "slo: %s  ttft=%s ms  tpot=%s ms  tokens=%s" % (
                verdict, root.get("ttft_ms", "-"),
                root.get("tpot_ms", "-"), root.get("num_tokens", "-")))
    if root.get("dossier_id"):
        lines.append(f"crash dossier: {root['dossier_id']}  "
                     f"(ray-tpu events --dossier {root['dossier_id']})")
    t0 = min((s.get("start", 0) for s in spans), default=0)

    def _walk(parent: Optional[str], depth: int) -> None:
        for s in by_parent.get(parent, []):
            status = s.get("status", "ok")
            mark = "" if status == "ok" else \
                f"  !{s.get('error_type') or status}"
            where = (s.get("worker_id") or "")[:8]
            extras = "".join(
                f"  {k}={s[k]}" for k in ("bytes", "npages", "num_tokens",
                                          "index")
                if s.get(k) is not None)
            lines.append(
                "%8.1fms  %s%-28s %8.1fms  [%s]%s%s" % (
                    (s.get("start", 0) - t0) * 1e3, "  " * depth,
                    s.get("name", "?")[:28], s.get("dur_ms", 0.0),
                    where or s.get("source", "?"), extras, mark))
            _walk(s.get("span_id"), depth + 1)

    _walk(None, 0)
    return "\n".join(lines)


def trace_timeline(trace_id: str, path: Optional[str] = None
                   ) -> List[dict]:
    """Perfetto export of ONE trace: its spans as complete slices merged
    with the cluster timeline's slices that carry the same trace id
    (task/queue-wait/STREAM_ITEM/PULL/HANDOFF/STEP events), so the
    request's hops and the subsystems they exercised share one time
    axis.  Load in chrome://tracing or ui.perfetto.dev."""
    trace = get_trace(trace_id)
    if not trace:
        return []
    tid_full = trace["trace_id"]
    events: List[dict] = []
    for s in trace.get("spans") or []:
        args = {k: v for k, v in s.items()
                if k not in ("start", "dur_ms", "name")}
        events.append({
            "name": s.get("name", "?"), "cat": f"span:{s.get('kind')}",
            "ph": "X", "ts": s.get("start", 0) * 1e6,
            "dur": max(1.0, float(s.get("dur_ms", 0.0)) * 1e3),
            "pid": f"trace {tid_full[:8]}",
            "tid": (s.get("source") or "proc") + ":" +
                   (s.get("worker_id") or "")[:8],
            "args": args,
        })
    # merge the subsystem slices stamped with this trace id (PULL /
    # HANDOFF / STEP / task / stream_item rows keep their own pid/tid —
    # the process axis — while the span rows group under the trace pid)
    for ev in timeline():
        if (ev.get("args") or {}).get("trace_id") == tid_full:
            events.append(ev)
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events


def get_dossier(dossier_id: str) -> Optional[dict]:
    """Crash dossier by id — a dead worker's id hex (prefix ok) or a
    dead node's id hex.  Contains the process's flight-recorder event
    ring, log tail and last metrics watermarks (docs/observability.md);
    ``format_dossier`` renders it for terminals."""
    return _gcs().call("get_dossier", {"dossier_id": dossier_id})


def list_dossiers() -> List[dict]:
    return _gcs().call("list_dossiers")


def node_health_table(nodes: List[dict]) -> List[str]:
    """Render the cluster health table off heartbeat-piggybacked
    snapshots — one renderer shared by ``metrics_summary()`` and
    ``ray-tpu status`` ([] when no node has reported health yet)."""
    rows = [n for n in nodes if n.get("health")]
    if not rows:
        return []
    lines = ["%-14s %-10s %6s %6s %6s %9s %s" % (
        "NODE", "STATE", "CPU", "MEM", "STORE", "LAG(ms)", "REASONS")]
    for n in rows:
        h = n["health"]
        state = "DEAD" if not n.get("alive") else (
            "DRAINING" if n.get("draining") else (
                "UNHEALTHY" if n.get("unhealthy") else "OK"))
        lines.append("%-14s %-10s %5.0f%% %5.0f%% %5.0f%% %9.0f %s" % (
            n["node_id"][:12], state,
            100 * h.get("cpu_frac", 0), 100 * h.get("mem_frac", 0),
            100 * h.get("store_frac", 0), h.get("loop_lag_ms", 0),
            ", ".join(n.get("unhealthy_reasons") or [])))
    return lines


# ----------------------------------------------------------------- fan-outs
def _each_raylet(fn):
    out = []
    for node in list_nodes():
        if not node.get("alive"):
            continue
        try:
            conn = rpc.connect(tuple(node["address"]))
        except OSError:
            continue
        try:
            out.append((node, fn(conn)))
        except (rpc.RpcError, ConnectionError, TimeoutError):
            pass
        finally:
            conn.close()
    return out


def list_workers(*, limit: int = 10000) -> List[dict]:
    workers: List[dict] = []
    for node, rows in _each_raylet(
            lambda c: c.call("list_workers", timeout=5)):
        for row in rows:
            row["node_id"] = node["node_id"]
            workers.append(row)
    return workers[:limit]


def _worker_stats() -> List[dict]:
    """core_worker_stats from every live worker + the local driver."""
    stats = []
    me = cw.get_global_worker()
    stats.append(me._rpc_core_worker_stats({}))
    for w in list_workers():
        if not w.get("alive") or not w.get("address"):
            continue
        try:
            conn = rpc.connect(tuple(w["address"]))
        except OSError:
            continue
        try:
            stats.append(conn.call("core_worker_stats", {}, timeout=5))
        except (rpc.RpcError, ConnectionError, TimeoutError):
            pass
        finally:
            conn.close()
    return stats


def list_objects(*, limit: int = 10000) -> List[dict]:
    objects: List[dict] = []
    for st in _worker_stats():
        for obj in st["objects"]:
            obj["owner_worker_id"] = st["worker_id"]
            obj["owner_mode"] = st["mode"]
            objects.append(obj)
    return objects[:limit]


# ---------------------------------------------------------------- summaries
def summarize_tasks(*, job_id: Optional[str] = None) -> Dict[str, Any]:
    summary: Dict[str, Dict[str, int]] = {}
    for t in list_tasks(job_id=job_id):
        per = summary.setdefault(t.get("name") or "<unknown>", {})
        per[t["state"]] = per.get(t["state"], 0) + 1
    return {"cluster": {"summary": summary,
                        "total_tasks": sum(sum(v.values())
                                           for v in summary.values())}}


def summarize_actors() -> Dict[str, Any]:
    summary: Dict[str, Dict[str, int]] = {}
    for a in list_actors():
        key = a.get("class_name") or a.get("name") or "<actor>"
        per = summary.setdefault(key, {})
        per[a["state"]] = per.get(a["state"], 0) + 1
    return {"cluster": {"summary": summary}}


def summarize_objects() -> Dict[str, Any]:
    total = count = inline = 0
    for o in list_objects():
        count += 1
        total += o.get("size", 0)
        inline += int(bool(o.get("inline")))
    return {"cluster": {"total_objects": count, "total_size_bytes": total,
                        "inline_objects": inline}}


def memory_summary() -> str:
    """Human-readable owned-object table (analog of `ray memory`)."""
    objects = list_objects()  # one cluster sweep for both table and totals
    lines = ["%-18s %-10s %-8s %-5s %-10s %s" % (
        "OBJECT_ID", "OWNER", "STATE", "REFS", "SIZE", "LOCATIONS")]
    total = 0
    for o in objects:
        total += o.get("size", 0)
        lines.append("%-18s %-10s %-8s %-5d %-10d %s" % (
            o["object_id"][:16] + "..", o["owner_worker_id"][:8],
            o["state"], o["refcount"], o.get("size", 0),
            ",".join(loc[:8] for loc in o.get("locations", []))))
    lines.append(f"--- {len(objects)} objects, {total} inline bytes ---")
    return "\n".join(lines)


# ----------------------------------------------------------------- timeline
def timeline(path: Optional[str] = None) -> List[dict]:
    """Chrome-trace (catapult) events from the GCS task table.

    Analog of `ray timeline` (/root/reference/python/ray/_private/
    state.py:829), RPC/stream-aware:

    * each task's RUNNING->FINISHED span is a complete ("X") event on
      its worker's row;
    * the SUBMITTED->RUNNING gap becomes a ``(queued)`` slice in the
      ``queue_wait`` category, so scheduling/lease latency is visible
      next to execution time;
    * streaming generators emit one instant ("i") per reported yield
      (``STREAM_ITEM`` task events), so per-item pacing and
      backpressure pauses show up between the task's start and end;
    * inter-node object pulls of a task's output appear as ``transfer``
      slices (``PULL`` events carrying duration/bytes/source count,
      docs/object_transfer.md) on the pulling process's row;
    * host-collective ops appear as ``collective`` slices
      (``COLLECTIVE`` events carrying op/algorithm/bytes/world size,
      docs/collective.md) on each participating rank's row;
    * disaggregated-serving KV handoffs appear as ``handoff`` slices
      (``HANDOFF`` events carrying stage/bytes/pages,
      docs/serve_disagg.md) on the exporting and importing replicas'
      rows;
    * every event carries the submitting span's ``trace_id`` in its
      args when one was propagated, so user spans (``span(...)``),
      tasks and stream items correlate in Perfetto.

    Load the output in chrome://tracing or ui.perfetto.dev.
    """
    events: List[dict] = []
    for t in list_tasks():
        start = end = None
        items = []
        pulls = []
        cols = []
        handoffs = []
        steps = []
        for ev in t.get("events", []):
            if ev["state"] == "RUNNING":
                start = ev["ts"]
            elif ev["state"] in ("FINISHED", "FAILED"):
                end = ev["ts"]
            elif ev["state"] == "STREAM_ITEM":
                items.append(ev)
            elif ev["state"] == "PULL":
                pulls.append(ev)
            elif ev["state"] == "COLLECTIVE":
                cols.append(ev)
            elif ev["state"] == "HANDOFF":
                handoffs.append(ev)
            elif ev["state"] == "STEP":
                steps.append(ev)
        for ev in steps:
            # one clocked train step (docs/observability.md): rides the
            # rank's synthetic step-<run>-r<rank> record.  The whole
            # step is one slice; its phase breakdown nests as
            # sub-slices stacked in canonical phase order, all stamped
            # with the step's trace_id so gang ranks correlate.
            dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
            t_start = ev["ts"] - dur_s
            pid = ev.get("node_id", t.get("node_id", "node"))[:8]
            tid = ev.get("worker_id", t.get("worker_id", "worker"))[:8]
            args = {"task_id": t["task_id"], "step": ev.get("step")}
            if ev.get("trace_id"):
                args["trace_id"] = ev["trace_id"]
            events.append({
                "name": f"step {ev.get('step', '?')}",
                "cat": "train_step", "ph": "X",
                "ts": t_start * 1e6, "dur": dur_s * 1e6,
                "pid": pid, "tid": tid, "args": dict(args),
            })
            phases = ev.get("phases") or {}
            from ray_tpu._private.step_stats import PHASES
            off = t_start
            ordered = [p for p in PHASES if p in phases] + \
                [p for p in sorted(phases) if p not in PHASES]
            for phase in ordered:
                p_dur = float(phases[phase]) / 1e3
                events.append({
                    "name": phase, "cat": "train_phase", "ph": "X",
                    "ts": off * 1e6, "dur": p_dur * 1e6,
                    "pid": pid, "tid": f"{tid}/phases",
                    "args": dict(args, phase=phase),
                })
                off += p_dur
        for ev in cols:
            # one host-collective op (docs/collective.md): rides the
            # rank's synthetic col-<group>-r<rank> record, which has no
            # lifecycle of its own — the slice stands alone on the
            # participating process's row
            dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
            events.append({
                "name": f"{ev.get('op', 'collective')}"
                        f"[{ev.get('algo', '?')}]"
                        f" ({ev.get('bytes', 0)} B)",
                "cat": "collective",
                "ph": "X",
                "ts": (ev["ts"] - dur_s) * 1e6,
                "dur": dur_s * 1e6,
                "pid": ev.get("node_id", t.get("node_id", "node"))[:8],
                "tid": ev.get("worker_id",
                              t.get("worker_id", "worker"))[:8],
                "args": {"task_id": t["task_id"],
                         "bytes": ev.get("bytes", 0),
                         "op": ev.get("op", ""),
                         "algo": ev.get("algo", ""),
                         "world": ev.get("world", 0)},
            })
        for ev in handoffs:
            # one export/import leg of a disaggregated-serving KV
            # handoff (docs/serve_disagg.md): rides a synthetic
            # ``handoff-<object>`` record with no lifecycle — the slice
            # stands alone on the exporting/importing replica's row
            dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
            events.append({
                "name": f"kv_handoff {ev.get('stage', '?')} "
                        f"({ev.get('bytes', 0)} B)",
                "cat": "handoff",
                "ph": "X",
                "ts": (ev["ts"] - dur_s) * 1e6,
                "dur": dur_s * 1e6,
                "pid": ev.get("node_id", t.get("node_id", "node"))[:8],
                "tid": ev.get("worker_id",
                              t.get("worker_id", "worker"))[:8],
                "args": {"task_id": t["task_id"],
                         "bytes": ev.get("bytes", 0),
                         "stage": ev.get("stage", ""),
                         "npages": ev.get("npages", 0)},
            })
        for ev in pulls:
            # a pull may happen long after the task finished (a borrower
            # fetching the output): its slice stands on its own
            dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
            events.append({
                "name": f"pull {ev.get('object_id', '?')[:12]} "
                        f"({ev.get('bytes', 0)} B)",
                "cat": "transfer",
                "ph": "X",
                "ts": (ev["ts"] - dur_s) * 1e6,
                "dur": dur_s * 1e6,
                # the slice belongs to the PULLING process's row (the
                # event stamps it); older events without the stamp fall
                # back to the producing task's row
                "pid": ev.get("node_id", t.get("node_id", "node"))[:8],
                "tid": ev.get("worker_id",
                              t.get("worker_id", "worker"))[:8],
                "args": {"task_id": t["task_id"],
                         "bytes": ev.get("bytes", 0),
                         "nsources": ev.get("nsources", 0)},
            })
        if start is None:
            continue
        if end is None or end < start:
            end = start
        pid = t.get("node_id", "node")[:8]
        tid = t.get("worker_id", "worker")[:8]
        args = {"task_id": t["task_id"], "state": t["state"]}
        if t.get("trace_id"):
            args["trace_id"] = t["trace_id"]
        queued = t.get("creation_time")
        if queued is not None and queued < start:
            # SUBMITTED -> RUNNING: the owner-side queue + lease wait
            events.append({
                "name": f"{t.get('name', 'task')} (queued)",
                "cat": "queue_wait",
                "ph": "X",
                "ts": queued * 1e6,
                "dur": (start - queued) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": dict(args),
            })
        events.append({
            "name": t.get("name", "task"),
            "cat": "task",
            "ph": "X",
            "ts": start * 1e6,
            "dur": (end - start) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        for ev in items:
            events.append({
                "name": f"{t.get('name', 'task')}[{ev.get('index', '?')}]",
                "cat": "stream_item",
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "ts": ev["ts"] * 1e6,
                "pid": pid,
                "tid": tid,
                "args": dict(args, index=ev.get("index")),
            })
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events


# ------------------------------------------------------------------ metrics
def list_metrics(prefix: str = "") -> List[dict]:
    """Cluster-wide metric series from the GCS KV ``metrics/`` namespace
    (user metrics AND the always-on runtime metrics), merged across
    processes.  One row per (metric, tag set): counters carry ``value``
    (summed); gauges carry ``value`` (summed — right for additive
    gauges like pin counts and pool sizes) AND ``max`` (the largest
    single process's reading — the honest aggregate for point-in-time
    or watermark gauges like queue depth); histograms carry
    ``count``/``sum``/``mean`` and bucket-estimated ``p50``/``p95``
    (each quantile reported as the upper bound of the bucket it lands
    in)."""
    gcs = _gcs()
    merged: Dict[tuple, dict] = {}
    for key in gcs.kv_keys("metrics/" + prefix):
        raw = gcs.kv_get(key)
        if not raw:
            continue
        try:
            _, name, _worker = key.split("/", 2)
            data = json.loads(raw)
        except ValueError:
            continue
        for tagjson, val in (data.get("values") or {}).items():
            row = merged.setdefault((name, tagjson), {
                "name": name,
                "type": data.get("type", "untyped"),
                "description": data.get("description", ""),
                "tags": dict(json.loads(tagjson)),
            })
            if isinstance(val, dict):      # histogram wire format
                row.setdefault("buckets", {})
                for le, n in (val.get("buckets") or {}).items():
                    row["buckets"][le] = row["buckets"].get(le, 0) + n
                row["sum"] = row.get("sum", 0.0) + val.get("sum", 0.0)
                row["count"] = row.get("count", 0) + val.get("count", 0)
            else:
                row["value"] = row.get("value", 0.0) + val
                if data.get("type") == "gauge":
                    row["max"] = max(row.get("max", float("-inf")), val)
    out = []
    for row in merged.values():
        if "buckets" in row:
            count = row.get("count", 0)
            row["mean"] = (row.get("sum", 0.0) / count) if count else 0.0
            row["p50"] = _bucket_quantile(row["buckets"], count, 0.5)
            row["p95"] = _bucket_quantile(row["buckets"], count, 0.95)
        out.append(row)
    out.sort(key=lambda r: (r["name"], sorted(r["tags"].items())))
    return out


def _bucket_quantile(buckets: Dict[str, int], count: int,
                     q: float) -> float:
    """Upper-bound estimate of quantile ``q`` from cumulative bucket
    counts; returns ``inf`` when it lands in the overflow bucket."""
    if not count:
        return 0.0
    target = q * count
    cum = 0
    for le in sorted((k for k in buckets if k not in ("+Inf", "inf")),
                     key=float):
        cum += buckets[le]
        if cum >= target:
            return float(le)
    return float("inf")


def metrics_summary() -> str:
    """Operator-facing runtime-telemetry table (``ray-tpu summary
    metrics``): top RPC methods by p50/p95, latency histograms, stream
    stalls, pin counts — telemetry without the dashboard."""
    rows = list_metrics()
    lines: List[str] = []

    # cluster event plane (docs/observability.md): top event types by
    # count plus unhealthy nodes — the single-screen summary covers
    # what happened, not just how fast
    try:
        stats = _gcs().call("cluster_event_stats", {})
        counts = stats.get("counts_by_type") or {}
    except (rpc.RpcError, ConnectionError, TimeoutError):
        stats, counts = {}, {}
    if counts:
        lines.append("== Cluster events ==")
        lines.append("%-34s %10s" % ("TYPE", "COUNT"))
        top = sorted(counts.items(), key=lambda kv: -kv[1])[:10]
        for etype, n in top:
            lines.append("%-34s %10d" % (etype[:34], n))
        lines.append("%-34s %10d  (%d retained, %d B)" % (
            "total", sum(counts.values()), stats.get("events", 0),
            stats.get("bytes", 0)))
        lines.append("")

    try:
        nodes = list_nodes()
    except (rpc.RpcError, ConnectionError, TimeoutError):
        nodes = []
    health_lines = node_health_table(nodes)
    if health_lines:
        lines.append("== Node health ==")
        lines.extend(health_lines)
        lines.append("")

    # object-transfer data plane (docs/object_transfer.md): regressions
    # visible without rerunning benchmarks/object_transfer_perf.py
    byname = {(r["name"], tuple(sorted(r["tags"].items()))): r
              for r in rows}

    def _scalar(name):
        row = byname.get((name, ()))
        return row.get("value", 0.0) if row else 0.0

    pulled = _scalar("ray_tpu_pull_bytes_total")
    rtt = byname.get(("ray_tpu_pull_chunk_rtt_ms", ()))
    local_hits = _scalar("ray_tpu_fetch_local_hits_total")
    remote = _scalar("ray_tpu_fetch_remote_pulls_total")
    pf_reqs = _scalar("ray_tpu_prefetch_requests_total")
    pf_hits = _scalar("ray_tpu_prefetch_hits_total")
    if pulled or remote or pf_reqs:
        lines.append("== Object transfer ==")
        lines.append("%-34s %14s" % ("bytes pulled", f"{pulled:,.0f}"))
        if rtt and rtt.get("count"):
            lines.append("%-34s %9.3g / %.3g ms" % (
                "chunk RTT p50/p95", rtt.get("p50", 0.0),
                rtt.get("p95", 0.0)))
        fetches = local_hits + remote
        if fetches:
            lines.append("%-34s %13.1f%%" % (
                "local-hit ratio (fetches)",
                100.0 * local_hits / fetches))
        if pf_reqs:
            lines.append("%-34s %13.1f%%" % (
                "prefetch hit ratio",
                100.0 * pf_hits / pf_reqs))
        lines.append("")

    # collective data plane (docs/collective.md): wire traffic by
    # codec, bytes the quantized path saved, per-algo op latency, and
    # how much async-op ring time overlapped the caller's compute
    wire_rows = [r for r in rows
                 if r["name"] == "ray_tpu_collective_wire_bytes"]
    saved = _scalar("ray_tpu_collective_bytes_saved_total")
    op_rows = [r for r in rows
               if r["name"] == "ray_tpu_collective_op_ms"
               and r.get("count")]
    if wire_rows or op_rows:
        lines.append("== Collective ==")
        for r in sorted(wire_rows,
                        key=lambda r: r["tags"].get("codec", "")):
            lines.append("%-34s %14s" % (
                f"wire bytes ({r['tags'].get('codec', '?')})",
                f"{r.get('value', 0.0):,.0f}"))
        if saved:
            lines.append("%-34s %14s" % ("bytes saved (quantized)",
                                         f"{saved:,.0f}"))
        for r in sorted(op_rows, key=lambda r: r["tags"].get("op", "")):
            lines.append("%-34s %10d %9.3g %9.3g" % (
                r["tags"].get("op", "?"), r["count"],
                r.get("p50", 0.0), r.get("p95", 0.0)))
        hid = byname.get(("ray_tpu_collective_overlap_hidden_ms", ()))
        if hid and hid.get("count"):
            wait = byname.get(("ray_tpu_collective_overlap_wait_ms", ()))
            lines.append("%-34s %9.3g / %.3g ms" % (
                "overlap hidden/waited p50",
                hid.get("p50", 0.0),
                (wait or {}).get("p50", 0.0)))
        lines.append("")

    # disaggregated serving (docs/serve_disagg.md): handoff movement
    # cost + per-pool latency, visible without the dashboard
    handoff_rows = [r for r in rows
                    if r["name"] in ("ray_tpu_serve_handoff_bytes",
                                     "ray_tpu_serve_handoff_ms")
                    and r.get("count")]
    if handoff_rows:
        lines.append("== Serve KV handoff ==")
        lines.append("%-34s %10s %9s %9s" % ("STAGE", "COUNT", "P50",
                                             "P95"))
        for r in sorted(handoff_rows,
                        key=lambda r: (r["name"],
                                       r["tags"].get("stage", ""))):
            unit = "B" if r["name"].endswith("bytes") else "ms"
            stage = r["tags"].get("stage", "?")
            lines.append("%-34s %10d %9.3g %9.3g" % (
                f"{stage} ({unit})", r["count"], r.get("p50", 0.0),
                r.get("p95", 0.0)))
        lines.append("")

    # training performance plane (docs/observability.md): per-phase
    # step clocks + the goodput ledger, visible without the dashboard
    phase_rows = [r for r in rows
                  if r["name"] == "ray_tpu_train_phase_ms"
                  and r.get("count")]
    step_rows = [r for r in rows if r["name"] == "ray_tpu_train_step_ms"
                 and r.get("count")]
    if phase_rows or step_rows:
        lines.append("== Training steps ==")
        lines.append("%-34s %10s %9s %9s" % ("PHASE", "COUNT", "P50",
                                             "P95"))
        for r in sorted(step_rows,
                        key=lambda r: r["tags"].get("run", "")):
            lines.append("%-34s %10d %9.3g %9.3g" % (
                f"step ({r['tags'].get('run', '?')[:24]})", r["count"],
                r.get("p50", 0.0), r.get("p95", 0.0)))
        for r in sorted(phase_rows,
                        key=lambda r: r["tags"].get("phase", "")):
            lines.append("%-34s %10d %9.3g %9.3g" % (
                r["tags"].get("phase", "?"), r["count"],
                r.get("p50", 0.0), r.get("p95", 0.0)))
        try:
            summary = training_summary()
        except (rpc.RpcError, ConnectionError, TimeoutError):
            summary = None
        agg = (summary or {}).get("aggregate")
        if agg:
            lines.append("latest run %s: goodput %.1f%%  mfu %.2f%%" % (
                (summary or {}).get("run", "?"),
                100 * agg.get("goodput", 0.0),
                100 * agg.get("mfu", 0.0)))
        lines.append("")

    # request tracing plane (docs/observability.md): trace volume, the
    # sampled fraction, and the worst SLO-violating routes with concrete
    # exemplar trace ids — `ray-tpu trace <id>` shows which hop ate the
    # budget
    try:
        tstats = trace_stats()
    except (rpc.RpcError, ConnectionError, TimeoutError):
        tstats = {}
    slo_rows = [r for r in rows
                if r["name"] in ("ray_tpu_serve_slo_good",
                                 "ray_tpu_serve_slo_violation")]
    if tstats.get("traces_seen") or slo_rows:
        lines.append("== Request traces ==")
        total_classified = sum(r.get("value", 0.0) for r in slo_rows
                               if r["tags"].get("slo") == "ttft")
        lines.append("%-34s %10d  (%d retained, %d spans, %d B)" % (
            "traces recorded", tstats.get("traces_seen", 0),
            tstats.get("traces", 0), tstats.get("spans", 0),
            tstats.get("bytes", 0)))
        if total_classified:
            # ingress roots only: counting task-submission traces here
            # would inflate the fraction past the real serve coverage
            lines.append("%-34s %13.1f%%  (%d requests SLO-classified)"
                         % ("sampled fraction",
                            100.0 * min(1.0, tstats.get("ingress_seen", 0)
                                        / total_classified),
                            total_classified))
        for r in sorted(slo_rows, key=lambda r: (
                r["tags"].get("pool", ""), r["tags"].get("slo", ""),
                r["name"])):
            lines.append("%-34s %14g" % (
                "slo %s %s{%s}" % (
                    "good" if r["name"].endswith("good") else "VIOLATION",
                    r["tags"].get("slo", "?"), r["tags"].get("pool", "?")),
                r.get("value", 0.0)))
        violating = sorted(
            ((route, s) for route, s in
             (tstats.get("slo_by_route") or {}).items()
             if s.get("violation")),
            key=lambda kv: -kv[1]["violation"])[:5]
        for route, s in violating:
            ex = (s.get("exemplars") or [{}])[0]
            lines.append("%-34s %6d violations  worst %sms  trace %s" % (
                f"route {route[:26]}", s["violation"],
                ex.get("ttft_ms", "?"),
                (ex.get("trace_id") or "?")[:16]))
        lines.append("")

    rpc_rows = [r for r in rows if r["name"] == "ray_tpu_rpc_dispatch_ms"
                and r.get("count")]
    if rpc_rows:
        rpc_rows.sort(key=lambda r: -r.get("p95", 0.0))
        lines.append("== RPC dispatch latency (ms) ==")
        lines.append("%-28s %10s %9s %9s" % ("METHOD", "COUNT", "P50",
                                             "P95"))
        for r in rpc_rows[:15]:
            lines.append("%-28s %10d %9.3g %9.3g" % (
                r["tags"].get("method", "?")[:28], r["count"],
                r.get("p50", 0.0), r.get("p95", 0.0)))
        lines.append("")

    hist_rows = [r for r in rows if r["type"] == "histogram"
                 and r["name"] != "ray_tpu_rpc_dispatch_ms"
                 and r.get("count")]
    if hist_rows:
        lines.append("== Latency / size distributions ==")
        lines.append("%-36s %10s %9s %9s %9s" % (
            "NAME", "COUNT", "MEAN", "P50", "P95"))
        for r in hist_rows:
            tag = ",".join(f"{k}={v}" for k, v in sorted(
                r["tags"].items()))
            name = r["name"] + (f"{{{tag}}}" if tag else "")
            lines.append("%-36s %10d %9.3g %9.3g %9.3g" % (
                name[:36], r["count"], r.get("mean", 0.0),
                r.get("p50", 0.0), r.get("p95", 0.0)))
        lines.append("")

    scalar_rows = [r for r in rows if r["type"] in ("counter", "gauge")
                   and "value" in r]
    if scalar_rows:
        lines.append("== Counters / gauges ==")
        for r in scalar_rows:
            extra = ""
            if "max" in r and r["max"] != r["value"]:
                extra = "  (max/process %g)" % r["max"]
            lines.append("%-44s %14g%s" % (r["name"][:44], r["value"],
                                           extra))

    return "\n".join(lines) if lines else "(no metrics published yet)"


# ------------------------------------------------------- sixth plane
# metrics history + recovery auditing + doctor (docs/observability.md)
def list_metrics_history(name: Optional[str] = None, *,
                         ident: Optional[str] = None,
                         since: Optional[float] = None,
                         resolution: Optional[float] = None,
                         limit: int = 2000) -> List[dict]:
    """Windowed metric points from the GCS history rings, oldest first
    (``resolution`` picks the ring with the closest bucket width; the
    finest by default).  Each point: ``ts``/``res_s``/``name``/
    ``ident``/``type``/``values`` — the flusher snapshot that closed
    that bucket."""
    return _gcs().call("list_metrics_history", {
        "name": name, "ident": ident, "since": since,
        "resolution": resolution, "limit": limit})


def metrics_history_stats(*, series: bool = False) -> dict:
    return _gcs().call("metrics_history_stats", {"series": series})


def list_recovery_episodes(kind: Optional[str] = None, *,
                           include_open: bool = True,
                           limit: int = 100) -> List[dict]:
    """Recovery episodes the auditor derived from the event plane:
    ``drain`` (NODE_PREEMPTING -> NODE_DRAINED), ``failover`` (first
    failure event -> TRAIN_GANG_RECOVERY) and ``heal``
    (REPLICA_RETIRED -> AUTOSCALE), each with ``latency_s`` and its
    SLO verdict."""
    return _gcs().call("list_recovery_episodes", {
        "kind": kind, "include_open": include_open, "limit": limit})


def recovery_stats() -> dict:
    return _gcs().call("recovery_stats", {})


def doctor_report() -> dict:
    """The cross-plane correlation report (``ray-tpu doctor``): ranked
    findings with evidence lines, assembled GCS-side from one snapshot
    of all six observability planes."""
    return _gcs().call("doctor_report", {})


def doctor_report_text() -> str:
    from ray_tpu._private.metrics_history import format_doctor_report
    return format_doctor_report(doctor_report())


def collect_debug_bundle(path: str) -> Dict[str, Any]:
    """One-shot forensics export (``ray-tpu debug-bundle``): a gzipped
    tarball of every observability plane as JSON — events + dossiers,
    traces, metrics (snapshot AND history window), step stats,
    recovery episodes, the doctor report (json + rendered text) and
    the merged Perfetto timeline.  Returns a manifest of member names
    and sizes so callers (and tests) can assert on the contents."""
    import io
    import tarfile
    import time as _time

    def _collect(fn):
        try:
            return fn()
        except Exception as e:   # a missing plane must not sink the rest
            return {"error": f"{type(e).__name__}: {e}"}

    gcs = _gcs()
    members: Dict[str, Any] = {
        "nodes.json": _collect(list_nodes),
        "events.json": _collect(
            lambda: list_cluster_events(limit=5000)),
        "event_stats.json": _collect(
            lambda: gcs.call("cluster_event_stats", {})),
        "dossiers.json": _collect(
            lambda: [get_dossier(d["dossier_id"]) or d
                     for d in list_dossiers()]),
        "traces.json": _collect(lambda: list_traces(limit=200)),
        "trace_stats.json": _collect(trace_stats),
        "metrics.json": _collect(list_metrics),
        "metrics_history.json": _collect(
            lambda: list_metrics_history(limit=10000)),
        "metrics_history_stats.json": _collect(
            lambda: metrics_history_stats(series=True)),
        "step_stats.json": _collect(lambda: list_step_stats()),
        "training_summary.json": _collect(training_summary),
        "recovery_episodes.json": _collect(
            lambda: list_recovery_episodes(limit=1000)),
        "recovery_stats.json": _collect(recovery_stats),
        "doctor.json": _collect(doctor_report),
        "timeline.json": _collect(timeline),
    }
    from ray_tpu._private.metrics_history import format_doctor_report
    members["doctor.txt"] = _collect(
        lambda: format_doctor_report(members["doctor.json"]))
    manifest = {"generated_ts": _time.time(), "members": {}}
    with tarfile.open(path, "w:gz") as tar:
        for name, payload in members.items():
            if name.endswith(".json"):
                blob = json.dumps(payload, indent=1,
                                  default=str).encode()
            else:
                blob = str(payload).encode()
            info = tarfile.TarInfo("debug-bundle/" + name)
            info.size = len(blob)
            info.mtime = int(_time.time())
            tar.addfile(info, io.BytesIO(blob))
            manifest["members"][name] = len(blob)
    return manifest
