"""State API: `list_*` / `summarize_*` / `memory_summary` / `timeline`.

Analog of /root/reference/python/ray/experimental/state/api.py (list_tasks
etc.), state_cli.py (`ray list tasks`), _private/state.py:829 (`ray
timeline` Chrome-trace export) and `ray memory` (refcount debugging).

Data sources: the GCS tables (tasks/actors/nodes/jobs/placement groups) and
live fan-out to raylets (`list_workers`) and core workers
(`core_worker_stats`) for objects — mirroring the reference's
StateDataSourceClient (state_manager.py:130).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu.runtime import core_worker as cw


def _gcs():
    return cw.get_global_worker().gcs


# --------------------------------------------------------------- GCS tables
def list_tasks(*, job_id: Optional[str] = None, state: Optional[str] = None,
               name: Optional[str] = None, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_task_events", {
        "job_id": job_id, "state": state, "name": name, "limit": limit})


def list_actors(*, state: Optional[str] = None,
                limit: int = 10000) -> List[dict]:
    actors = _gcs().call("list_actors")
    if state:
        actors = [a for a in actors if a.get("state") == state]
    return actors[:limit]


def list_nodes(*, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_nodes")[:limit]


def list_jobs(*, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_jobs")[:limit]


def list_placement_groups(*, limit: int = 10000) -> List[dict]:
    return _gcs().call("list_placement_groups")[:limit]


# ----------------------------------------------------------------- fan-outs
def _each_raylet(fn):
    out = []
    for node in list_nodes():
        if not node.get("alive"):
            continue
        try:
            conn = rpc.connect(tuple(node["address"]))
        except OSError:
            continue
        try:
            out.append((node, fn(conn)))
        except (rpc.RpcError, ConnectionError, TimeoutError):
            pass
        finally:
            conn.close()
    return out


def list_workers(*, limit: int = 10000) -> List[dict]:
    workers: List[dict] = []
    for node, rows in _each_raylet(
            lambda c: c.call("list_workers", timeout=5)):
        for row in rows:
            row["node_id"] = node["node_id"]
            workers.append(row)
    return workers[:limit]


def _worker_stats() -> List[dict]:
    """core_worker_stats from every live worker + the local driver."""
    stats = []
    me = cw.get_global_worker()
    stats.append(me._rpc_core_worker_stats({}))
    for w in list_workers():
        if not w.get("alive") or not w.get("address"):
            continue
        try:
            conn = rpc.connect(tuple(w["address"]))
        except OSError:
            continue
        try:
            stats.append(conn.call("core_worker_stats", {}, timeout=5))
        except (rpc.RpcError, ConnectionError, TimeoutError):
            pass
        finally:
            conn.close()
    return stats


def list_objects(*, limit: int = 10000) -> List[dict]:
    objects: List[dict] = []
    for st in _worker_stats():
        for obj in st["objects"]:
            obj["owner_worker_id"] = st["worker_id"]
            obj["owner_mode"] = st["mode"]
            objects.append(obj)
    return objects[:limit]


# ---------------------------------------------------------------- summaries
def summarize_tasks(*, job_id: Optional[str] = None) -> Dict[str, Any]:
    summary: Dict[str, Dict[str, int]] = {}
    for t in list_tasks(job_id=job_id):
        per = summary.setdefault(t.get("name") or "<unknown>", {})
        per[t["state"]] = per.get(t["state"], 0) + 1
    return {"cluster": {"summary": summary,
                        "total_tasks": sum(sum(v.values())
                                           for v in summary.values())}}


def summarize_actors() -> Dict[str, Any]:
    summary: Dict[str, Dict[str, int]] = {}
    for a in list_actors():
        key = a.get("class_name") or a.get("name") or "<actor>"
        per = summary.setdefault(key, {})
        per[a["state"]] = per.get(a["state"], 0) + 1
    return {"cluster": {"summary": summary}}


def summarize_objects() -> Dict[str, Any]:
    total = count = inline = 0
    for o in list_objects():
        count += 1
        total += o.get("size", 0)
        inline += int(bool(o.get("inline")))
    return {"cluster": {"total_objects": count, "total_size_bytes": total,
                        "inline_objects": inline}}


def memory_summary() -> str:
    """Human-readable owned-object table (analog of `ray memory`)."""
    objects = list_objects()  # one cluster sweep for both table and totals
    lines = ["%-18s %-10s %-8s %-5s %-10s %s" % (
        "OBJECT_ID", "OWNER", "STATE", "REFS", "SIZE", "LOCATIONS")]
    total = 0
    for o in objects:
        total += o.get("size", 0)
        lines.append("%-18s %-10s %-8s %-5d %-10d %s" % (
            o["object_id"][:16] + "..", o["owner_worker_id"][:8],
            o["state"], o["refcount"], o.get("size", 0),
            ",".join(loc[:8] for loc in o.get("locations", []))))
    lines.append(f"--- {len(objects)} objects, {total} inline bytes ---")
    return "\n".join(lines)


# ----------------------------------------------------------------- timeline
def timeline(path: Optional[str] = None) -> List[dict]:
    """Chrome-trace (catapult) events from the GCS task table.

    Analog of `ray timeline` (/root/reference/python/ray/_private/
    state.py:829): each task's RUNNING->FINISHED span becomes a complete
    ("X") event on its worker's row; load the output in chrome://tracing
    or Perfetto.
    """
    events: List[dict] = []
    for t in list_tasks():
        start = end = None
        for ev in t.get("events", []):
            if ev["state"] == "RUNNING":
                start = ev["ts"]
            elif ev["state"] in ("FINISHED", "FAILED"):
                end = ev["ts"]
        if start is None:
            continue
        if end is None or end < start:
            end = start
        events.append({
            "name": t.get("name", "task"),
            "cat": "task",
            "ph": "X",
            "ts": start * 1e6,
            "dur": (end - start) * 1e6,
            "pid": t.get("node_id", "node")[:8],
            "tid": t.get("worker_id", "worker")[:8],
            "args": {"task_id": t["task_id"], "state": t["state"]},
        })
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events
