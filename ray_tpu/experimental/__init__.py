"""Experimental APIs: state introspection, internal KV.

Analog of /root/reference/python/ray/experimental/.
"""
