"""Thin client over the GCS internal key-value store.

Analog of /root/reference/python/ray/experimental/internal_kv.py — the
cluster-wide KV used for function exports, named resources, and library
metadata (Serve config, collective rendezvous, ...).
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.runtime import core_worker as cw


def _gcs():
    return cw.get_global_worker().gcs


def _internal_kv_initialized() -> bool:
    return cw._global_worker is not None


def _internal_kv_put(key: str, value: bytes, overwrite: bool = True) -> bool:
    """Returns True iff the key already existed (reference semantics)."""
    if isinstance(value, str):
        value = value.encode()
    return _gcs().kv_put(key, value, overwrite=overwrite)


def _internal_kv_get(key: str) -> Optional[bytes]:
    return _gcs().kv_get(key)


def _internal_kv_exists(key: str) -> bool:
    return bool(_gcs().call("kv_exists", {"key": key}))


def _internal_kv_del(key: str) -> bool:
    return _gcs().kv_del(key)


def _internal_kv_list(prefix: str) -> List[str]:
    return list(_gcs().call("kv_keys", {"prefix": prefix}))
