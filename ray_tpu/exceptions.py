"""Public exception hierarchy.

Analog of the reference's /root/reference/python/ray/exceptions.py: errors are
first-class object payloads — a failed task's return object *contains* the
exception, so ``get`` raises it at the caller with cause chaining.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class _DossierRef:
    """Mixin: errors caused by a process death carry a ``dossier_id``
    (the dead worker's id hex, or a node id hex) referencing the crash
    dossier the raylet/GCS harvested — event ring, log tail, metrics
    watermarks (docs/observability.md).  ``debug_dossier()`` fetches
    and pretty-prints it at the driver."""

    dossier_id: str | None = None

    def debug_dossier(self, timeout: float = 10.0) -> str:
        """Fetch + format this death's crash dossier from the GCS.

        Returns the formatted dossier text; a descriptive placeholder
        when no dossier reference exists or it already rotated out."""
        did = self.dossier_id
        if not did:
            cause = getattr(self, "cause", None)
            if isinstance(cause, _DossierRef) and cause.dossier_id:
                return cause.debug_dossier(timeout)
            return "(no dossier reference on this error)"
        from ray_tpu._private.cluster_events import (fetch_dossier,
                                                     format_dossier)
        try:
            d = fetch_dossier(did, timeout)
        except Exception as e:  # noqa: BLE001 - diagnostics must not raise
            return f"(dossier {did[:12]} fetch failed: {e})"
        if not d:
            return f"(dossier {did[:12]} not found — rotated out, or " \
                   "the cluster is gone)"
        return format_dossier(d)


class TaskError(RayTpuError, _DossierRef):
    """A task raised an exception during execution (cf. RayTaskError)."""

    def __init__(self, function_name: str = "", cause: BaseException | None = None,
                 traceback_str: str = ""):
        self.function_name = function_name
        self.cause = cause
        self.traceback_str = traceback_str
        super().__init__(
            f"task {function_name!r} failed: {cause!r}\n{traceback_str}")

    def __reduce__(self):
        # Exception's default reduce would reconstruct with the FORMATTED
        # message as function_name, re-wrapping the error on every pickle
        # round trip (messages grew exponentially down task chains).
        # The state dict keeps the dossier reference across the wire.
        return (TaskError, (self.function_name, self.cause,
                            self.traceback_str),
                {"dossier_id": self.dossier_id})


class WorkerCrashedError(RayTpuError, _DossierRef):
    """The worker process executing the task died (cf. WorkerCrashedError)."""

    def __init__(self, message: str = "worker crashed",
                 dossier_id: str | None = None):
        self.dossier_id = dossier_id
        super().__init__(message)

    def __reduce__(self):
        return (WorkerCrashedError, (self.args[0] if self.args else "",
                                     self.dossier_id))


class ActorDiedError(RayTpuError, _DossierRef):
    """The actor is dead and will not be restarted (cf. RayActorError)."""

    def __init__(self, reason: str = "actor died",
                 dossier_id: str | None = None):
        self.reason = reason
        self.dossier_id = dossier_id
        super().__init__(reason)

    def __reduce__(self):
        return (ActorDiedError, (self.reason, self.dossier_id))


class ActorUnavailableError(RayTpuError, _DossierRef):
    """The actor is temporarily unreachable (restart pending)."""


class ObjectLostError(RayTpuError, _DossierRef):
    """The object's primary copy was lost and could not be
    reconstructed.  When lineage is exhausted the error names the node
    dossier of the node that lost the last copy
    (``err.debug_dossier()``; docs/fault_tolerance.md)."""

    def __init__(self, message: str = "object lost",
                 dossier_id: str | None = None):
        self.dossier_id = dossier_id
        super().__init__(message)
        # default exception pickling round-trips (cls, args) + __dict__,
        # which carries dossier_id — no custom __reduce__ needed


class ObjectStoreFullError(RayTpuError):
    """The shared-memory store could not admit the object."""


class OutOfDiskError(RayTpuError):
    """Local disk crossed local_fs_capacity_threshold: spilling and
    fallback allocation refuse to write (reference OutOfDiskError)."""


class OutOfMemoryError(RayTpuError, _DossierRef):
    """A worker was killed by the memory monitor (cf. OutOfMemoryError)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running (cf. TaskCancelledError)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(..., timeout=)`` expired (cf. GetTimeoutError)."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing the task/actor runtime environment failed."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's max_pending_calls backpressure limit hit."""


class SchedulingError(RayTpuError):
    """A scheduling strategy can never be satisfied (placement group
    removed, bundle index out of range, hard affinity to a dead node) —
    permanent, not retried."""


class ChannelError(RayTpuError):
    """Base class for compiled-DAG shared-memory channel errors
    (experimental/channel.py)."""


class ChannelTimeoutError(ChannelError, TimeoutError):
    """A blocking channel read/write did not complete in time."""


class ChannelClosedError(ChannelError):
    """The channel was poisoned (teardown, or a participant died): no
    further items will ever arrive, blocked peers must unwind."""


class DAGCompileError(RayTpuError):
    """``experimental_compile()`` rejected the graph (not actor-method
    only, no/duplicate InputNode, cycle, dead actor, remote actor, ...)."""


class DAGUnavailableError(RayTpuError):
    """A compiled DAG lost a participating actor (or was torn down) and
    can no longer execute; recompile to get a fresh one — the compiled-
    graph analog of ObjectLostError."""


class KVPoolFullError(RayTpuError):
    """A disaggregated-serving KV handoff could not be admitted: the
    decode engine's import wait queue is at its configured cap
    (``import_queue_max``).  Raised synchronously at submit — a fast
    typed rejection the serving layer uses to re-queue / re-route the
    handoff to another replica instead of piling more waiters onto a
    saturated pool (docs/serve_disagg.md)."""
