"""In-process simulated multi-node clusters for tests.

Analog of /root/reference/python/ray/cluster_utils.py (Cluster :99,
add_node :165, remove_node :238): multiple raylet daemons as separate OS
processes on one machine, each with its own shm store and resource pool,
against one GCS — node-failure tests without VMs (SURVEY.md §4 tier 3).
"""

from __future__ import annotations

import subprocess
import time
from typing import Dict, List, Optional

from ray_tpu.runtime.node import NodeProcesses, new_session_dir


class ClusterNode:
    def __init__(self, proc: subprocess.Popen, node_id: str,
                 address, store_path: str):
        self.proc = proc
        self.node_id = node_id
        self.address = tuple(address)
        self.store_path = store_path


class Cluster:
    def __init__(self, head_resources: Optional[Dict[str, float]] = None,
                 object_store_memory: int = 128 * 1024 * 1024):
        self.session_dir = new_session_dir()
        self._node_procs = NodeProcesses(self.session_dir)
        self.gcs_address = self._node_procs.start_gcs()
        self._object_store_memory = object_store_memory
        self.nodes: List[ClusterNode] = []
        self.head_node = self.add_node(resources=head_resources)

    @property
    def address(self) -> str:
        return f"{self.gcs_address[0]}:{self.gcs_address[1]}"

    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None) -> ClusterNode:
        import json
        import os
        import sys

        from ray_tpu.runtime.node import _spawn, _wait_address_file
        addr_file = f"{self.session_dir}/raylet_{len(self.nodes)}_" \
                    f"{int(time.time() * 1e6)}.json"
        cmd = [sys.executable, "-m", "ray_tpu.runtime.raylet",
               "--gcs-host", self.gcs_address[0],
               "--gcs-port", str(self.gcs_address[1]),
               "--session-dir", self.session_dir,
               "--address-file", addr_file,
               "--object-store-memory",
               str(object_store_memory or self._object_store_memory)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        proc = _spawn(cmd, self.session_dir,
                      f"raylet_{len(self.nodes)}")
        info = _wait_address_file(addr_file, proc)
        node = ClusterNode(proc, info["node_id"],
                           (info["host"], info["port"]), info["store_path"])
        self.nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode, sigkill: bool = True) -> None:
        if node.proc.poll() is None:
            if sigkill:
                node.proc.kill()
            else:
                node.proc.terminate()
            node.proc.wait(timeout=10)
        if node in self.nodes:
            self.nodes.remove(node)

    def kill_gcs(self) -> None:
        if self._node_procs.gcs_proc is not None:
            self._node_procs.gcs_proc.kill()
            self._node_procs.gcs_proc.wait(timeout=10)

    def restart_gcs(self) -> None:
        """Kill the GCS and restart it on the same port: it replays its
        file snapshot and raylets re-attach on their next heartbeat
        (reference: test_gcs_fault_tolerance.py restart pattern)."""
        import os
        port = self.gcs_address[1]
        self.kill_gcs()
        addr_file = os.path.join(self.session_dir, "gcs_address.json")
        try:
            os.remove(addr_file)  # never report the dead server's address
        except FileNotFoundError:
            pass
        deadline = time.monotonic() + 30
        while True:
            try:
                self._node_procs.start_gcs(port=port)
                return
            except (RuntimeError, TimeoutError):
                # the dead process's port may linger in TIME_WAIT briefly
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 30.0) -> None:
        from ray_tpu.runtime.gcs import GcsClient
        want = count if count is not None else len(self.nodes)
        client = GcsClient(self.gcs_address)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                alive = [n for n in client.call("list_nodes") if n["alive"]]
                if len(alive) >= want:
                    return
                time.sleep(0.1)
            raise TimeoutError(f"only {len(alive)} of {want} nodes alive")
        finally:
            client.close()

    def shutdown(self) -> None:
        import ray_tpu
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        for node in list(self.nodes):
            try:
                self.remove_node(node)
            except Exception:
                pass
        self._node_procs.stop()


class AutoscalingCluster:
    """A head node plus a live autoscaler Monitor over the fake provider.

    Analog of /root/reference/python/ray/cluster_utils.py:24
    ``AutoscalingCluster``: runs the real StandardAutoscaler loop against
    raylet subprocesses so tests exercise demand-driven scale-up/down
    (SURVEY.md §4, test_autoscaler_fake_multinode.py).
    """

    def __init__(self, config: dict,
                 head_resources: Optional[Dict[str, float]] = None,
                 poll_period_s: float = 0.5):
        from ray_tpu.autoscaler.monitor import Monitor
        self.cluster = Cluster(head_resources=head_resources or {"CPU": 1})
        cfg = dict(config)
        cfg.setdefault("provider", {"type": "fake"})
        self.monitor = Monitor(self.cluster.gcs_address, cfg,
                               session_dir=self.cluster.session_dir,
                               poll_period_s=poll_period_s)
        self.monitor.start()

    @property
    def address(self) -> str:
        return self.cluster.address

    def shutdown(self) -> None:
        self.monitor.stop()
        self.cluster.shutdown()
