"""Durable DAG executor.

Analog of /root/reference/python/ray/workflow/workflow_executor.py (:32)
+ workflow_state_from_dag.py: flattens the DAG into steps with
deterministic IDs (topological index + callable name — stable across a
re-built identical DAG, which is what resume() relies on), executes each
step as a ray_tpu task, and checkpoints every result before dependents
consume it.
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.dag import ClassMethodNode, ClassNode, DAGNode, FunctionNode, InputNode
from ray_tpu.workflow import storage as st


class WorkflowCancellationError(Exception):
    """Raised inside a running workflow when cancel() flips its status."""


def _step_ids(dag: DAGNode) -> Dict[str, str]:
    """node uuid -> deterministic step id."""
    ids = {}
    for i, node in enumerate(dag.walk()):
        if isinstance(node, FunctionNode):
            name = node._remote_function._func.__name__
        elif isinstance(node, ClassNode):
            name = node._actor_class._cls.__name__
        elif isinstance(node, ClassMethodNode):
            name = node._method_name
        else:
            name = type(node).__name__
        ids[node._stable_uuid] = f"{i:04d}_{name}"
    return ids


def _run_step_with_retries(storage, workflow_id, step_id, fn, args, kwargs,
                           wf_opts: Dict[str, Any]) -> Any:
    """One durable step attempt loop: re-submit up to max_retries times
    with exponential backoff; with catch_exceptions the step resolves to
    ``(result, None)`` / ``(None, exception)`` instead of failing."""
    import time

    max_retries = int(wf_opts.get("max_retries", 0))
    backoff = float(wf_opts.get("retry_backoff_s", 0.2))
    catch = bool(wf_opts.get("catch_exceptions", False))
    attempt = 0
    while True:
        # cancel() must be able to stop a retry loop (especially the
        # retry-forever case) — the pre-step check alone can't reach here
        if storage.get_status(workflow_id) == st.STATUS_CANCELED:
            raise WorkflowCancellationError(workflow_id)
        try:
            value = ray_tpu.get(fn.remote(*args, **kwargs))
            return (value, None) if catch else value
        except Exception as e:  # noqa: BLE001 - user step errors
            # negative max_retries means retry forever (reference
            # convention for infinite step retries)
            if 0 <= max_retries <= attempt:
                if catch:
                    return None, e
                storage.save_step_exception(workflow_id, step_id, e)
                raise
            time.sleep(min(backoff * (2 ** min(attempt, 16)), 30.0))
            attempt += 1


def execute_workflow(storage: st.WorkflowStorage, workflow_id: str,
                     dag: DAGNode, input_value: Any = None) -> Any:
    """Run the DAG durably; returns the final result value.

    Completed steps (from a previous run of the same workflow_id) are
    loaded from storage instead of re-executed.
    """
    ids = _step_ids(dag)
    cache: Dict[str, Any] = {}

    def execute_node(node: DAGNode) -> Any:
        if node._stable_uuid in cache:
            return cache[node._stable_uuid]
        step_id = ids[node._stable_uuid]

        if storage.get_status(workflow_id) == st.STATUS_CANCELED:
            raise WorkflowCancellationError(workflow_id)

        if isinstance(node, InputNode):
            value = input_value
        elif isinstance(node, ClassNode):
            # actors are transient (recreated on every run/resume), so their
            # method steps are NOT durable: skipping a checkpointed method
            # call would leave the fresh actor's state behind (wrong
            # results). Only stateless FunctionNode steps checkpoint.
            args, kwargs = _resolve(node)
            cls = node._actor_class
            if node._options:
                cls = cls.options(**node._options)
            value = cls.remote(*args, **kwargs)
        elif isinstance(node, ClassMethodNode):
            handle = execute_node(node._class_node)
            args, kwargs = _resolve(node)
            ref = getattr(handle, node._method_name).remote(*args, **kwargs)
            try:
                value = ray_tpu.get(ref)
            except Exception as e:
                storage.save_step_exception(workflow_id, step_id, e)
                raise
        elif storage.has_step_result(workflow_id, step_id):
            value = storage.load_step_result(workflow_id, step_id)
        elif isinstance(node, FunctionNode):
            args, kwargs = _resolve(node)
            opts = dict(node._options or {})
            # step durability options (workflow.options(...)): retries
            # with backoff + catch_exceptions (reference step options)
            wf_opts = opts.pop("_workflow", {})
            fn = node._remote_function
            if opts:
                fn = fn.options(**opts)
            value = _run_step_with_retries(
                storage, workflow_id, step_id, fn, args, kwargs, wf_opts)
            storage.save_step_result(workflow_id, step_id, value)
        else:
            raise TypeError(f"cannot execute {type(node).__name__}")
        cache[node._stable_uuid] = value
        return value

    def _resolve(node: DAGNode):
        args = tuple(execute_node(a) if isinstance(a, DAGNode) else a
                     for a in node._bound_args)
        kwargs = {k: (execute_node(v) if isinstance(v, DAGNode) else v)
                  for k, v in node._bound_kwargs.items()}
        return args, kwargs

    try:
        result = execute_node(dag)
        # output checkpoint BEFORE the status flip: a crash between the two
        # must never yield SUCCESS-with-no-output
        storage.save_step_result(workflow_id, "__output__", result)
        storage.set_status(workflow_id, st.STATUS_SUCCESS)
        return result
    except WorkflowCancellationError:
        raise
    except Exception:
        if storage.get_status(workflow_id) != st.STATUS_CANCELED:
            storage.set_status(workflow_id, st.STATUS_FAILED)
        raise
