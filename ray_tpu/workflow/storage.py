"""Filesystem workflow storage.

Analog of /root/reference/python/ray/workflow/workflow_storage.py: one
directory per workflow, one per step; step results are written atomically
(tmp + rename) so a crash mid-write never yields a corrupt checkpoint.
Layout:

    {base}/{workflow_id}/status                    RUNNING|SUCCESS|FAILED|CANCELED
    {base}/{workflow_id}/steps/{step_id}/result.pkl
    {base}/{workflow_id}/steps/{step_id}/exception.pkl
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Any, List, Optional

import cloudpickle

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESS = "SUCCESS"
STATUS_FAILED = "FAILED"
STATUS_CANCELED = "CANCELED"


class WorkflowStorage:
    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    # ------------------------------------------------------------ workflows
    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.base_dir, workflow_id)

    def create_workflow(self, workflow_id: str) -> None:
        os.makedirs(os.path.join(self._wf_dir(workflow_id), "steps"),
                    exist_ok=True)
        self.set_status(workflow_id, STATUS_RUNNING)

    def workflow_exists(self, workflow_id: str) -> bool:
        return os.path.isdir(self._wf_dir(workflow_id))

    def set_status(self, workflow_id: str, status: str) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "status"),
            status.encode())

    def get_status(self, workflow_id: str) -> Optional[str]:
        try:
            with open(os.path.join(self._wf_dir(workflow_id), "status"),
                      "rb") as f:
                return f.read().decode()
        except FileNotFoundError:
            return None

    def list_workflows(self) -> List[str]:
        try:
            return sorted(
                d for d in os.listdir(self.base_dir)
                if os.path.isdir(self._wf_dir(d)))
        except FileNotFoundError:
            return []

    def delete_workflow(self, workflow_id: str) -> None:
        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    # ---------------------------------------------------------------- steps
    def _step_dir(self, workflow_id: str, step_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps", step_id)

    def has_step_result(self, workflow_id: str, step_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._step_dir(workflow_id, step_id), "result.pkl"))

    def save_step_result(self, workflow_id: str, step_id: str,
                         result: Any) -> None:
        d = self._step_dir(workflow_id, step_id)
        os.makedirs(d, exist_ok=True)
        self._atomic_write(os.path.join(d, "result.pkl"),
                           cloudpickle.dumps(result))

    def load_step_result(self, workflow_id: str, step_id: str) -> Any:
        with open(os.path.join(self._step_dir(workflow_id, step_id),
                               "result.pkl"), "rb") as f:
            return cloudpickle.loads(f.read())

    def save_step_exception(self, workflow_id: str, step_id: str,
                            err: BaseException) -> None:
        d = self._step_dir(workflow_id, step_id)
        os.makedirs(d, exist_ok=True)
        try:
            data = cloudpickle.dumps(err)
        except Exception:  # noqa: BLE001 - unpicklable exception
            data = cloudpickle.dumps(RuntimeError(repr(err)))
        self._atomic_write(os.path.join(d, "exception.pkl"), data)

    # ------------------------------------------------------------------ dag
    def save_dag(self, workflow_id: str, dag_bytes: bytes) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "dag.pkl"), dag_bytes)

    def load_dag(self, workflow_id: str) -> bytes:
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
                  "rb") as f:
            return f.read()

    def dag_digest(self, workflow_id: str) -> Optional[str]:
        try:
            return hashlib.sha256(self.load_dag(workflow_id)).hexdigest()
        except FileNotFoundError:
            return None

    def clear_steps(self, workflow_id: str) -> None:
        """Drop all step checkpoints (the DAG changed; old results would be
        silently wrong for new step ids that happen to collide)."""
        shutil.rmtree(os.path.join(self._wf_dir(workflow_id), "steps"),
                      ignore_errors=True)

    # ---------------------------------------------------------------- misc
    # -------------------------------------------------------- virtual actors
    def _actor_path(self, actor_id: str) -> str:
        return os.path.join(self.base_dir, "virtual_actors",
                            f"{actor_id}.pkl")

    def actor_exists(self, actor_id: str) -> bool:
        return os.path.exists(self._actor_path(actor_id))

    def save_actor_state(self, actor_id: str, state_bytes: bytes) -> None:
        path = self._actor_path(actor_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._atomic_write(path, state_bytes)

    def load_actor_state(self, actor_id: str) -> bytes:
        with open(self._actor_path(actor_id), "rb") as f:
            return f.read()

    def delete_actor(self, actor_id: str) -> None:
        try:
            os.remove(self._actor_path(actor_id))
        except FileNotFoundError:
            pass

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
