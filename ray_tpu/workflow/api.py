"""Public Workflow API.

Analog of /root/reference/python/ray/workflow/api.py: run/run_async/
resume/get_output/get_status/list_all/cancel/delete. The DAG and input are
pickled into storage at submission, so ``resume`` needs only the
workflow_id (matching reference workflow recovery semantics).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Any, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode
from ray_tpu.workflow import storage as st
from ray_tpu.workflow.executor import execute_workflow

_storage: Optional[st.WorkflowStorage] = None
_lock = threading.Lock()


def init(storage_dir: Optional[str] = None) -> None:
    global _storage
    with _lock:
        if storage_dir is None:
            storage_dir = os.environ.get(
                "RAY_TPU_WORKFLOW_DIR",
                os.path.expanduser("~/.ray_tpu/workflows"))
        _storage = st.WorkflowStorage(storage_dir)


def _get_storage() -> st.WorkflowStorage:
    if _storage is None:
        init()
    return _storage


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Run a DAG durably to completion; returns the final value."""
    storage = _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    dag_bytes = cloudpickle.dumps((dag, input_value))
    if not storage.workflow_exists(workflow_id):
        storage.create_workflow(workflow_id)
    else:
        # re-running an existing id: stale checkpoints from a *different*
        # DAG must not be served (step ids are positional and would collide)
        import hashlib
        if storage.dag_digest(workflow_id) != \
                hashlib.sha256(dag_bytes).hexdigest():
            storage.clear_steps(workflow_id)
        storage.set_status(workflow_id, st.STATUS_RUNNING)
    # always persist THIS dag so a later resume() replays what actually ran
    storage.save_dag(workflow_id, dag_bytes)
    return execute_workflow(storage, workflow_id, dag, input_value)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None) -> Tuple[str, "ray_tpu.ObjectRef"]:
    """Submit and return (workflow_id, ref-like thread result).

    Runs the executor on a driver-side thread (steps themselves are remote
    tasks); returns a handle whose .result() joins it.
    """
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    out: dict = {}
    done = threading.Event()

    def target():
        try:
            out["value"] = run(dag, workflow_id=workflow_id,
                               input_value=input_value)
        except BaseException as e:  # noqa: BLE001
            out["error"] = e
        done.set()

    threading.Thread(target=target, daemon=True).start()

    class _Future:
        def result(self, timeout: Optional[float] = None):
            if not done.wait(timeout):
                raise TimeoutError("workflow still running")
            if "error" in out:
                raise out["error"]
            return out["value"]

    return workflow_id, _Future()


def resume(workflow_id: str) -> Any:
    """Re-run a stored workflow; completed steps load from checkpoints."""
    storage = _get_storage()
    if not storage.workflow_exists(workflow_id):
        raise ValueError(f"no workflow {workflow_id!r}")
    dag, input_value = cloudpickle.loads(storage.load_dag(workflow_id))
    storage.set_status(workflow_id, st.STATUS_RUNNING)
    return execute_workflow(storage, workflow_id, dag, input_value)


def get_output(workflow_id: str) -> Any:
    storage = _get_storage()
    if storage.has_step_result(workflow_id, "__output__"):
        return storage.load_step_result(workflow_id, "__output__")
    raise ValueError(f"workflow {workflow_id!r} has no output "
                     f"(status={storage.get_status(workflow_id)})")


def get_status(workflow_id: str) -> Optional[str]:
    return _get_storage().get_status(workflow_id)


def list_all() -> List[Tuple[str, str]]:
    storage = _get_storage()
    return [(wid, storage.get_status(wid))
            for wid in storage.list_workflows()]


def cancel(workflow_id: str) -> None:
    """Flag a workflow canceled; the executor checks before each step and
    stops with WorkflowCancellationError (already-submitted step tasks run
    to completion, matching reference cancel semantics)."""
    _get_storage().set_status(workflow_id, st.STATUS_CANCELED)


def delete(workflow_id: str) -> None:
    _get_storage().delete_workflow(workflow_id)
