"""Public Workflow API.

Analog of /root/reference/python/ray/workflow/api.py: run/run_async/
resume/get_output/get_status/list_all/cancel/delete. The DAG and input are
pickled into storage at submission, so ``resume`` needs only the
workflow_id (matching reference workflow recovery semantics).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Any, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode
from ray_tpu.workflow import storage as st
from ray_tpu.workflow.executor import execute_workflow

_storage: Optional[st.WorkflowStorage] = None
_lock = threading.Lock()


def init(storage_dir: Optional[str] = None) -> None:
    global _storage
    with _lock:
        if storage_dir is None:
            storage_dir = os.environ.get(
                "RAY_TPU_WORKFLOW_DIR",
                os.path.expanduser("~/.ray_tpu/workflows"))
        _storage = st.WorkflowStorage(storage_dir)


def _get_storage() -> st.WorkflowStorage:
    if _storage is None:
        init()
    return _storage


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Run a DAG durably to completion; returns the final value."""
    storage = _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    dag_bytes = cloudpickle.dumps((dag, input_value))
    if not storage.workflow_exists(workflow_id):
        storage.create_workflow(workflow_id)
    else:
        # re-running an existing id: stale checkpoints from a *different*
        # DAG must not be served (step ids are positional and would collide)
        import hashlib
        if storage.dag_digest(workflow_id) != \
                hashlib.sha256(dag_bytes).hexdigest():
            storage.clear_steps(workflow_id)
        storage.set_status(workflow_id, st.STATUS_RUNNING)
    # always persist THIS dag so a later resume() replays what actually ran
    storage.save_dag(workflow_id, dag_bytes)
    return execute_workflow(storage, workflow_id, dag, input_value)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None) -> Tuple[str, "ray_tpu.ObjectRef"]:
    """Submit and return (workflow_id, ref-like thread result).

    Runs the executor on a driver-side thread (steps themselves are remote
    tasks); returns a handle whose .result() joins it.
    """
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    out: dict = {}
    done = threading.Event()

    def target():
        try:
            out["value"] = run(dag, workflow_id=workflow_id,
                               input_value=input_value)
        except BaseException as e:  # noqa: BLE001
            out["error"] = e
        done.set()

    threading.Thread(target=target, daemon=True).start()

    class _Future:
        def result(self, timeout: Optional[float] = None):
            if not done.wait(timeout):
                raise TimeoutError("workflow still running")
            if "error" in out:
                raise out["error"]
            return out["value"]

    return workflow_id, _Future()


def resume(workflow_id: str) -> Any:
    """Re-run a stored workflow; completed steps load from checkpoints."""
    storage = _get_storage()
    if not storage.workflow_exists(workflow_id):
        raise ValueError(f"no workflow {workflow_id!r}")
    dag, input_value = cloudpickle.loads(storage.load_dag(workflow_id))
    storage.set_status(workflow_id, st.STATUS_RUNNING)
    return execute_workflow(storage, workflow_id, dag, input_value)


def get_output(workflow_id: str) -> Any:
    storage = _get_storage()
    if storage.has_step_result(workflow_id, "__output__"):
        return storage.load_step_result(workflow_id, "__output__")
    raise ValueError(f"workflow {workflow_id!r} has no output "
                     f"(status={storage.get_status(workflow_id)})")


def get_status(workflow_id: str) -> Optional[str]:
    return _get_storage().get_status(workflow_id)


def list_all() -> List[Tuple[str, str]]:
    storage = _get_storage()
    return [(wid, storage.get_status(wid))
            for wid in storage.list_workflows()]


def options(*, max_retries: int = 0, retry_backoff_s: float = 0.2,
            catch_exceptions: bool = False) -> dict:
    """Step-level durability options, merged into a bound node's options:
    ``fn.options(**workflow.options(max_retries=3)).bind(...)``
    (cf. reference workflow.options / step max_retries+catch_exceptions).
    Retries re-run the step task with exponential backoff;
    ``catch_exceptions`` turns the step's value into ``(result, None)`` /
    ``(None, exception)`` instead of failing the workflow."""
    return {"_workflow": {"max_retries": int(max_retries),
                          "retry_backoff_s": float(retry_backoff_s),
                          "catch_exceptions": bool(catch_exceptions)}}


class EventListener:
    """Poll-based event source (cf. reference workflow.event listeners,
    python/ray/workflow/event_listener.py — asyncio there, polling here).
    Subclass and implement ``poll_for_event() -> Optional[Any]``: return
    None while the event hasn't happened, the payload once it has.  The
    payload checkpoints like any step result, so a resumed workflow sees
    the event exactly once and never re-waits."""

    def poll_for_event(self):
        raise NotImplementedError


def _wait_for_event_step(packed):
    import time as _time

    import cloudpickle as _cp
    cls, a, interval, timeout = _cp.loads(packed)
    listener = cls(*a)
    deadline = None if timeout is None else _time.monotonic() + timeout
    while True:
        event = listener.poll_for_event()
        if event is not None:
            return event
        if deadline is not None and _time.monotonic() >= deadline:
            raise TimeoutError(
                f"no event from {cls.__name__} within {timeout}s")
        _time.sleep(interval)


# module-level remote fns (one function export total, not one per call)
_wait_for_event_remote = ray_tpu.remote(_wait_for_event_step)


def wait_for_event(listener_cls, *args, poll_interval_s: float = 0.5,
                   timeout_s: Optional[float] = None) -> DAGNode:
    """A DAG node that completes when the listener observes its event.

    Runs as a normal (durable) workflow step: a remote task instantiates
    ``listener_cls(*args)`` and polls until the event arrives (or
    ``timeout_s`` expires -> TimeoutError fails the step)."""
    blob = cloudpickle.dumps((listener_cls, args, poll_interval_s,
                              timeout_s))
    return _wait_for_event_remote.bind(blob)


# ------------------------------------------------------------ virtual actors
def _virtual_actor_step(packed):
    import cloudpickle as _cp
    cls, state, meth, a, kw = _cp.loads(packed)
    instance = cls.__new__(cls)
    instance.__dict__.update(_cp.loads(state))
    result = getattr(instance, meth)(*a, **kw)
    return _cp.dumps(instance.__dict__), result


_virtual_actor_remote = ray_tpu.remote(_virtual_actor_step)


class VirtualActorMethod:
    def __init__(self, handle: "VirtualActorHandle", name: str):
        self._handle = handle
        self._name = name

    def run(self, *args, **kwargs) -> Any:
        return self._handle._invoke(self._name, args, kwargs)


class VirtualActorHandle:
    """Durable actor: state lives in workflow storage, each method call is
    a step that loads state -> executes in a remote task -> checkpoints the
    new state before returning (cf. reference experimental workflow virtual
    actors).  Single-writer per actor id; state must be cloudpicklable."""

    def __init__(self, cls, actor_id: str):
        self._cls = cls
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> VirtualActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return VirtualActorMethod(self, name)

    def _invoke(self, method: str, args: tuple, kwargs: dict) -> Any:
        storage = _get_storage()
        state_bytes = storage.load_actor_state(self._actor_id)
        blob = cloudpickle.dumps((self._cls, state_bytes, method, args,
                                  kwargs))
        new_state, result = ray_tpu.get(_virtual_actor_remote.remote(blob))
        storage.save_actor_state(self._actor_id, new_state)
        return result


class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls

    def get_or_create(self, actor_id: str, *args, **kwargs
                      ) -> VirtualActorHandle:
        storage = _get_storage()
        if not storage.actor_exists(actor_id):
            instance = self._cls(*args, **kwargs)
            storage.save_actor_state(
                actor_id, cloudpickle.dumps(instance.__dict__))
        return VirtualActorHandle(self._cls, actor_id)


def virtual_actor(cls) -> VirtualActorClass:
    """``@workflow.virtual_actor`` — durable-state actor decorator."""
    return VirtualActorClass(cls)


def get_virtual_actor(cls_or_vac, actor_id: str) -> VirtualActorHandle:
    """Handle to an existing virtual actor (raises if it doesn't exist)."""
    storage = _get_storage()
    if not storage.actor_exists(actor_id):
        raise ValueError(f"no virtual actor {actor_id!r}")
    cls = cls_or_vac._cls if isinstance(cls_or_vac, VirtualActorClass) \
        else cls_or_vac
    return VirtualActorHandle(cls, actor_id)


def cancel(workflow_id: str) -> None:
    """Flag a workflow canceled; the executor checks before each step and
    stops with WorkflowCancellationError (already-submitted step tasks run
    to completion, matching reference cancel semantics)."""
    _get_storage().set_status(workflow_id, st.STATUS_CANCELED)


def delete(workflow_id: str) -> None:
    _get_storage().delete_workflow(workflow_id)
