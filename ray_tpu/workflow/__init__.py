"""Workflow: durable DAG execution with checkpointed steps and resume.

Analog of /root/reference/python/ray/workflow (WorkflowExecutor
workflow_executor.py:32, workflow_state_from_dag.py, workflow_storage.py):
a DAG authored with ``.bind()`` runs step-by-step; each step's result is
persisted to workflow storage before dependents run, so a crashed or
cancelled workflow resumes from its last completed step.
"""

from ray_tpu.workflow.api import (EventListener, cancel, delete,
                                  get_output, get_status,
                                  get_virtual_actor, init, list_all,
                                  options, resume, run, run_async,
                                  virtual_actor, wait_for_event)
from ray_tpu.workflow.storage import WorkflowStorage

__all__ = ["init", "run", "run_async", "resume", "get_output", "get_status",
           "list_all", "cancel", "delete", "WorkflowStorage", "options",
           "EventListener", "wait_for_event", "virtual_actor",
           "get_virtual_actor"]
