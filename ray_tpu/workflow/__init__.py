"""Workflow: durable DAG execution with checkpointed steps and resume.

Analog of /root/reference/python/ray/workflow (WorkflowExecutor
workflow_executor.py:32, workflow_state_from_dag.py, workflow_storage.py):
a DAG authored with ``.bind()`` runs step-by-step; each step's result is
persisted to workflow storage before dependents run, so a crashed or
cancelled workflow resumes from its last completed step.
"""

from ray_tpu.workflow.api import (cancel, delete, get_output, get_status,
                                  init, list_all, resume, run, run_async)
from ray_tpu.workflow.storage import WorkflowStorage

__all__ = ["init", "run", "run_async", "resume", "get_output", "get_status",
           "list_all", "cancel", "delete", "WorkflowStorage"]
