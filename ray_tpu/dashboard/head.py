"""Dashboard head: aiohttp server over GCS state.

Cite: /root/reference/python/ray/dashboard/head.py + http_server_head.py
(aiohttp), modules/node, modules/actor, modules/job (job_head.py REST),
modules/metrics. The server needs no driver attachment: it reads the GCS
tables with a plain GcsClient and fans out to raylets/workers over RPC —
same data sources as the reference's StateAPIManager.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from ray_tpu.runtime.gcs import GcsClient


class DashboardHead:
    def __init__(self, gcs_address: Tuple[str, int],
                 host: str = "127.0.0.1", port: int = 8265):
        self.gcs = GcsClient(gcs_address, connect_retry=True)
        self.gcs_address = tuple(gcs_address)
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dashboard-head")
        self._thread.start()
        if not self._started.wait(15):
            raise TimeoutError("dashboard failed to start")
        return (self.host, self.port)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self.gcs.close()
        except Exception:
            pass

    def _serve(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        app = web.Application()
        app.add_routes([
            web.get("/api/version", self._version),
            web.get("/api/nodes", self._nodes),
            web.get("/api/actors", self._actors),
            web.get("/api/tasks", self._tasks),
            web.get("/api/placement_groups", self._pgs),
            web.get("/api/cluster_status", self._cluster_status),
            web.get("/api/jobs", self._jobs),
            web.post("/api/jobs", self._submit_job),
            web.get("/api/jobs/{submission_id}", self._job_info),
            web.get("/api/jobs/{submission_id}/logs", self._job_logs),
            web.post("/api/jobs/{submission_id}/stop", self._job_stop),
            web.get("/api/serve/applications", self._serve_status),
            web.get("/api/events", self._events),
            web.get("/events", self._events),
            web.get("/api/dossiers", self._dossiers),
            web.get("/api/dossiers/{dossier_id}", self._dossier),
            web.get("/api/training", self._training),
            web.get("/api/traces", self._traces),
            web.get("/api/traces/{trace_id}", self._trace),
            web.get("/api/history", self._history),
            web.get("/api/recovery", self._recovery),
            web.get("/api/doctor", self._doctor),
            web.get("/api/profile", self._profile),
            web.get("/metrics", self._metrics),
            web.get("/", self._index),
        ])
        runner = web.AppRunner(app)
        self._loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        self._loop.run_until_complete(site.start())
        self.port = site._server.sockets[0].getsockname()[1]
        self._runner = runner
        self._started.set()
        self._loop.run_forever()
        self._loop.run_until_complete(runner.cleanup())

    # ------------------------------------------------------------ blocking
    # GCS/RPC calls are synchronous; run them off the event loop.
    async def _call(self, fn, *args):
        return await asyncio.get_event_loop().run_in_executor(
            None, fn, *args)

    # ------------------------------------------------------------- handlers
    async def _index(self, request) -> web.Response:
        if "application/json" in request.headers.get("Accept", ""):
            return web.json_response({
                "service": "ray_tpu dashboard",
                "routes": ["/api/version", "/api/nodes", "/api/actors",
                           "/api/tasks", "/api/placement_groups",
                           "/api/cluster_status", "/api/jobs",
                           "/api/serve/applications", "/metrics"]})
        from ray_tpu.dashboard.web_app import INDEX_HTML
        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _version(self, request) -> web.Response:
        import ray_tpu
        return web.json_response({"version": ray_tpu.__version__})

    async def _nodes(self, request) -> web.Response:
        nodes = await self._call(self.gcs.call, "list_nodes")
        return web.json_response({"nodes": nodes})

    async def _actors(self, request) -> web.Response:
        actors = await self._call(self.gcs.call, "list_actors")
        return web.json_response({"actors": actors})

    async def _tasks(self, request) -> web.Response:
        limit = int(request.query.get("limit", 1000))
        tasks = await self._call(
            lambda: self.gcs.call("list_task_events", {"limit": limit}))
        return web.json_response({"tasks": tasks})

    async def _pgs(self, request) -> web.Response:
        pgs = await self._call(self.gcs.call, "list_placement_groups")
        return web.json_response({"placement_groups": pgs})

    async def _cluster_status(self, request) -> web.Response:
        nodes = await self._call(self.gcs.call, "list_nodes")
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in nodes:
            if not n.get("alive"):
                continue
            for r, v in n.get("resources", {}).items():
                total[r] = total.get(r, 0) + v
            for r, v in n.get("available", {}).items():
                avail[r] = avail.get(r, 0) + v
        return web.json_response({
            "alive_nodes": sum(bool(n.get("alive")) for n in nodes),
            "dead_nodes": sum(not n.get("alive") for n in nodes),
            "total_resources": total,
            "available_resources": avail,
        })

    async def _serve_status(self, request) -> web.Response:
        """Serve controller status (published to GCS KV each reconcile)."""
        import json
        raw = await self._call(self.gcs.kv_get, "serve:status")
        deployments = json.loads(raw) if raw else {}
        return web.json_response({"deployments": deployments})

    # ---------------------------------------------------------------- jobs
    def _job_kv(self, prefix: str) -> List[dict]:
        out = []
        for key in self.gcs.kv_keys(prefix):
            raw = self.gcs.kv_get(key)
            if raw:
                out.append(json.loads(raw))
        return out

    async def _jobs(self, request) -> web.Response:
        jobs = await self._call(self._job_kv, "job_submission:")
        return web.json_response({"jobs": jobs})

    async def _job_info(self, request) -> web.Response:
        sid = request.match_info["submission_id"]
        raw = await self._call(self.gcs.kv_get, "job_submission:" + sid)
        if raw is None:
            raise web.HTTPNotFound(text=f"job {sid} not found")
        return web.json_response(json.loads(raw))

    async def _job_logs(self, request) -> web.Response:
        """Full text by default; ``?offset=N`` returns the delta past N as
        JSON so the live page can tail without refetching (reference
        job_head.py tail_job_logs streaming)."""
        sid = request.match_info["submission_id"]
        raw = await self._call(self.gcs.kv_get, "job_logs:" + sid)
        text = (raw or b"").decode("utf-8", "replace")
        if "offset" in request.query:
            try:
                off = max(0, int(request.query["offset"]))
            except ValueError:
                raise web.HTTPBadRequest(
                    text="offset must be an integer") from None
            return web.json_response(
                {"text": text[off:], "offset": len(text)})
        return web.Response(text=text)

    async def _job_stop(self, request) -> web.Response:
        sid = request.match_info["submission_id"]
        await self._call(
            lambda: self.gcs.kv_put("job_stop:" + sid, b"1"))
        return web.json_response({"stopped": True})

    async def _submit_job(self, request) -> web.Response:
        """REST job submission (reference job_head.py POST /api/jobs/)."""
        body = await request.json()
        entrypoint = body.get("entrypoint")
        if not entrypoint:
            raise web.HTTPBadRequest(text="missing 'entrypoint'")

        def _submit() -> str:
            import ray_tpu
            from ray_tpu.job_submission import JobSubmissionClient
            if not ray_tpu.is_initialized():
                client = JobSubmissionClient(
                    f"{self.gcs_address[0]}:{self.gcs_address[1]}")
            else:
                client = JobSubmissionClient()
            return client.submit_job(
                entrypoint=entrypoint,
                submission_id=body.get("submission_id"),
                metadata=body.get("metadata"),
                runtime_env=body.get("runtime_env"))

        sid = await self._call(_submit)
        return web.json_response({"submission_id": sid})

    # --------------------------------------------------------------- events
    async def _events(self, request) -> web.Response:
        """Cluster event plane (docs/observability.md): typed lifecycle
        events with node/worker/actor/severity/type filters."""
        try:
            limit = int(request.query.get("limit", 200))
        except ValueError:
            raise web.HTTPBadRequest(text="limit must be an integer") \
                from None
        q = request.query
        events = await self._call(
            lambda: self.gcs.call("list_cluster_events", {
                "limit": limit, "severity": q.get("severity"),
                "min_severity": q.get("min_severity"),
                "type": q.get("type"), "node_id": q.get("node_id"),
                "worker_id": q.get("worker_id"),
                "actor_id": q.get("actor_id"),
                "job_id": q.get("job_id"),
                "source": q.get("source")}))
        return web.json_response({"events": events})

    async def _dossiers(self, request) -> web.Response:
        out = await self._call(lambda: self.gcs.call("list_dossiers"))
        return web.json_response({"dossiers": out})

    async def _history(self, request) -> web.Response:
        """Metrics-history plane (docs/observability.md): windowed
        points per series from the GCS retention rings."""
        q = request.query
        try:
            limit = int(q.get("limit", 2000))
            since = float(q["since"]) if "since" in q else None
            resolution = (float(q["resolution"])
                          if "resolution" in q else None)
        except ValueError:
            raise web.HTTPBadRequest(
                text="limit/since/resolution must be numeric") from None
        points = await self._call(
            lambda: self.gcs.call("list_metrics_history", {
                "name": q.get("name"), "ident": q.get("ident"),
                "since": since, "resolution": resolution,
                "limit": limit}))
        stats = await self._call(
            lambda: self.gcs.call("metrics_history_stats", {}))
        return web.json_response({"points": points, "stats": stats})

    async def _recovery(self, request) -> web.Response:
        """Recovery auditor: derived drain/failover/heal episodes with
        SLO verdicts, plus the rotation-surviving counters."""
        q = request.query
        try:
            limit = int(q.get("limit", 100))
        except ValueError:
            raise web.HTTPBadRequest(text="limit must be an integer") \
                from None
        episodes = await self._call(
            lambda: self.gcs.call("list_recovery_episodes", {
                "kind": q.get("kind"), "limit": limit}))
        stats = await self._call(
            lambda: self.gcs.call("recovery_stats", {}))
        return web.json_response({"episodes": episodes, "stats": stats})

    async def _doctor(self, request) -> web.Response:
        """Cross-plane correlation report (ranked findings)."""
        report = await self._call(
            lambda: self.gcs.call("doctor_report", {}))
        return web.json_response(report)

    async def _dossier(self, request) -> web.Response:
        """One crash dossier; ``?format=text`` pretty-prints it."""
        did = request.match_info["dossier_id"]
        d = await self._call(
            lambda: self.gcs.call("get_dossier", {"dossier_id": did}))
        if d is None:
            raise web.HTTPNotFound(text=f"dossier {did} not found")
        if request.query.get("format") == "text":
            from ray_tpu._private.cluster_events import format_dossier
            return web.Response(text=format_dossier(d))
        return web.json_response(d)

    # ------------------------------------------------------------- training
    async def _training(self, request) -> web.Response:
        """Training performance plane (docs/observability.md):
        ?run=<id-or-group-prefix> — run directory + step skew + the
        goodput-ledger summary of the selected (default latest) run."""
        run = request.query.get("run")

        def build():
            table = self.gcs.call("list_step_stats",
                                  {"run": run, "limit": 50})
            table["summary"] = self.gcs.call("training_summary",
                                             {"run": run})
            return table

        return web.json_response(await self._call(build))

    # -------------------------------------------------------------- profile
    async def _traces(self, request) -> web.Response:
        """Request-trace directory (docs/observability.md tracing
        plane); ?slo_violations=1 narrows to SLO misses."""
        q = request.query
        rows = await self._call(
            lambda: self.gcs.call("list_traces", {
                "slo_violations": q.get("slo_violations") in ("1", "true"),
                "route": q.get("route"),
                "limit": int(q.get("limit", 100))}))
        stats = await self._call(
            lambda: self.gcs.call("trace_stats", {}))
        return web.json_response({"traces": rows, "stats": stats})

    async def _trace(self, request) -> web.Response:
        trace = await self._call(
            lambda: self.gcs.call(
                "get_trace",
                {"trace_id": request.match_info["trace_id"]}))
        if trace is None:
            return web.json_response({"error": "no such trace"},
                                     status=404)
        return web.json_response({"trace": trace})

    async def _profile(self, request) -> web.Response:
        """On-demand flame sampling of any cluster process (reference
        reporter_agent CPU profiling): ?node_id=...[&worker_id=...]
        [&duration=2][&format=folded|top]."""
        node_prefix = request.query.get("node_id")
        try:
            # clamp: an unbounded duration would pin an executor thread
            # and the target's sampler for its whole span
            duration = min(60.0,
                           float(request.query.get("duration", 2.0)))
        except ValueError:
            raise web.HTTPBadRequest(text="duration must be a number") \
                from None
        fmt = request.query.get("format", "folded")

        def run():
            from ray_tpu._private import rpc as _rpc
            from ray_tpu._private.profiler import folded_text, top_summary
            if node_prefix:
                nodes = self.gcs.call("list_nodes")
                node = next((n for n in nodes
                             if n["node_id"].startswith(node_prefix)
                             and n.get("alive")), None)
                if node is None:
                    raise ValueError(f"no alive node matching "
                                     f"{node_prefix!r}")
                conn = _rpc.connect(tuple(node["address"]), timeout=5.0)
                try:
                    counts = conn.call(
                        "profile",
                        {"duration": duration,
                         "worker_id": request.query.get("worker_id")},
                        timeout=duration + 40)
                finally:
                    conn.close()
            else:
                counts = self.gcs.call("profile", {"duration": duration},
                                       timeout=duration + 40)
            return top_summary(counts) if fmt == "top" \
                else folded_text(counts)

        try:
            text = await self._call(run)
        except Exception as e:  # noqa: BLE001 - surfaced as HTTP 400
            raise web.HTTPBadRequest(text=str(e))
        return web.Response(text=text)

    # -------------------------------------------------------------- metrics
    async def _metrics(self, request) -> web.Response:
        """Prometheus text exposition of user + runtime metrics and
        cluster gauges (reference modules/metrics + metrics_agent
        prometheus_exporter).  Both metric families live in the GCS KV
        ``metrics/`` namespace in one wire format; histograms render as
        conformant cumulative ``_bucket{le=...}``/``_count``/``_sum``
        series (runtime_metrics.prometheus_exposition)."""
        def build() -> str:
            from ray_tpu._private.runtime_metrics import \
                prometheus_exposition
            entries = []
            for key in sorted(self.gcs.kv_keys("metrics/")):
                raw = self.gcs.kv_get(key)
                if not raw:
                    continue
                _, name, worker = key.split("/", 2)
                try:
                    entries.append((name, worker, json.loads(raw)))
                except ValueError:
                    continue
            lines: List[str] = []
            text = prometheus_exposition(entries)
            if text:
                lines.append(text)
            # built-in cluster gauges
            nodes = self.gcs.call("list_nodes")
            alive = [n for n in nodes if n.get("alive")]
            lines.append("# TYPE ray_tpu_cluster_nodes gauge")
            lines.append(f"ray_tpu_cluster_nodes {len(alive)}")
            for res in ("CPU", "TPU"):
                total = sum(n["resources"].get(res, 0) for n in alive)
                avail = sum(n["available"].get(res, 0) for n in alive)
                lines.append(f"# TYPE ray_tpu_{res.lower()}_total gauge")
                lines.append(f"ray_tpu_{res.lower()}_total {total}")
                lines.append(
                    f"# TYPE ray_tpu_{res.lower()}_available gauge")
                lines.append(f"ray_tpu_{res.lower()}_available {avail}")
            return "\n".join(lines) + "\n"

        text = await self._call(build)
        return web.Response(text=text,
                            content_type="text/plain")


def start_dashboard(gcs_address: Tuple[str, int], host: str = "127.0.0.1",
                    port: int = 8265) -> DashboardHead:
    head = DashboardHead(gcs_address, host=host, port=port)
    head.start()
    return head
