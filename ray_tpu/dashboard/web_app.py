"""Live dashboard frontend: one self-contained HTML+JS page.

Replaces the reference's React SPA (dashboard/client/src/App.tsx — pages
for overview/nodes/actors/jobs/logs/serve) with a no-build-toolchain
single file served by ``DashboardHead``: vanilla JS polls the existing
REST API every 2 s, so every view updates without reload; the Jobs view
tails a job's logs live through the offset-based log endpoint.

Design notes: status is never color-alone (dot + text label), duration
bars use a single muted hue (magnitude = one-hue sequential), all text
stays in ink tokens.
"""

INDEX_HTML = r"""<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root {
    --bg: #f7f7f5; --surface: #ffffff; --ink: #1a1a1a; --ink2: #5c5c57;
    --muted: #8a8a84; --line: #e4e4df; --accent: #4c6a92;
    --ok: #2e7d48; --warn: #a66a00; --bad: #b3382e;
  }
  * { box-sizing: border-box; }
  body { margin: 0; font: 14px/1.45 system-ui, sans-serif;
         background: var(--bg); color: var(--ink); }
  header { display: flex; align-items: baseline; gap: 16px;
           padding: 14px 20px; background: var(--surface);
           border-bottom: 1px solid var(--line); }
  header h1 { font-size: 16px; margin: 0; }
  header .sub { color: var(--muted); font-size: 12px; }
  nav { display: flex; gap: 2px; padding: 0 20px;
        background: var(--surface); border-bottom: 1px solid var(--line); }
  nav button { border: 0; background: none; padding: 10px 14px;
               font: inherit; color: var(--ink2); cursor: pointer;
               border-bottom: 2px solid transparent; }
  nav button.active { color: var(--ink);
                      border-bottom-color: var(--accent); }
  main { padding: 18px 20px; max-width: 1200px; }
  .tiles { display: flex; gap: 12px; flex-wrap: wrap; margin-bottom: 16px; }
  .tile { background: var(--surface); border: 1px solid var(--line);
          border-radius: 8px; padding: 12px 16px; min-width: 150px; }
  .tile .v { font-size: 22px; font-weight: 600; }
  .tile .k { color: var(--muted); font-size: 12px; }
  table { border-collapse: collapse; width: 100%;
          background: var(--surface); border: 1px solid var(--line);
          border-radius: 8px; overflow: hidden; }
  th, td { text-align: left; padding: 7px 12px;
           border-bottom: 1px solid var(--line); font-size: 13px; }
  th { color: var(--ink2); font-weight: 600; background: var(--bg); }
  tr:last-child td { border-bottom: 0; }
  .dot { display: inline-block; width: 8px; height: 8px;
         border-radius: 50%; margin-right: 6px; vertical-align: middle; }
  .s-ok .dot { background: var(--ok); }   .s-ok { color: var(--ok); }
  .s-warn .dot { background: var(--warn); } .s-warn { color: var(--warn); }
  .s-bad .dot { background: var(--bad); }  .s-bad { color: var(--bad); }
  .s-mut .dot { background: var(--muted); } .s-mut { color: var(--ink2); }
  .bar { background: var(--line); border-radius: 4px; height: 8px;
         width: 160px; display: inline-block; vertical-align: middle; }
  .bar i { display: block; height: 100%; background: var(--accent);
           border-radius: 4px; }
  .mono { font-family: ui-monospace, monospace; font-size: 12px; }
  #log { background: #16211c; color: #d7e0da; padding: 12px;
         border-radius: 8px; font-family: ui-monospace, monospace;
         font-size: 12px; white-space: pre-wrap; max-height: 420px;
         overflow-y: auto; margin-top: 12px; }
  .hint { color: var(--muted); font-size: 12px; margin: 8px 0; }
  a.joblink { color: var(--accent); cursor: pointer;
              text-decoration: underline; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="sub" id="meta">connecting…</span>
  <span class="sub" id="tick"></span>
</header>
<nav id="nav"></nav>
<main id="main">loading…</main>
<script>
const TABS = ["Overview", "Metrics", "Nodes", "Actors", "Tasks",
              "Timeline", "Training", "Traces", "Jobs", "Serve",
              "Placement Groups", "Events"];
let tab = location.hash ? decodeURIComponent(location.hash.slice(1))
                        : "Overview";
let followJob = null, logOffset = 0, timer = null;

const $ = (id) => document.getElementById(id);
const esc = (s) => String(s ?? "").replace(/[&<>"'`]/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;",
           "'":"&#39;","`":"&#96;"}[c]));
const J = async (url) => (await fetch(url)).json();

function statusCls(s) {
  s = String(s || "").toUpperCase();
  if (["ALIVE", "RUNNING", "SUCCEEDED", "CREATED", "HEALTHY", "FINISHED",
       "TRUE"].includes(s)) return "s-ok";
  if (["PENDING", "PENDING_CREATION", "RESTARTING", "UPDATING",
       "SUBMITTED", "WARNING"].includes(s)) return "s-warn";
  if (["DEAD", "FAILED", "ERROR", "STOPPED", "FALSE"].includes(s))
    return "s-bad";
  return "s-mut";
}
const badge = (s) => `<span class="${statusCls(s)}"><span class="dot">` +
  `</span>${esc(s)}</span>`;
function table(cols, rows) {
  return `<table><tr>${cols.map(c => `<th>${esc(c)}</th>`).join("")}</tr>` +
    (rows.length ? rows.map(r =>
       `<tr>${r.map(c => `<td>${c}</td>`).join("")}</tr>`).join("")
     : `<tr><td colspan="${cols.length}" class="hint">nothing yet</td></tr>`)
    + `</table>`;
}

async function renderOverview() {
  const s = await J("/api/cluster_status");
  const res = s.total_resources || {}, av = s.available_resources || {};
  const tiles = [
    ["alive nodes", s.alive_nodes], ["dead nodes", s.dead_nodes],
    ...Object.keys(res).sort().map(r =>
      [r, `${(av[r] ?? 0).toFixed(1)} / ${res[r].toFixed(1)} free`]),
  ];
  return `<div class="tiles">` + tiles.map(([k, v]) =>
    `<div class="tile"><div class="v">${esc(v)}</div>` +
    `<div class="k">${esc(k)}</div></div>`).join("") + `</div>` +
    `<div class="hint">auto-refreshing every 2 s — API under /api/*, ` +
    `Prometheus at /metrics</div>`;
}

// ---- Metrics: rolling client-side history, sampled even while other
// tabs are open so the charts have depth the moment you switch here
// (the reference embeds Grafana; one SVG line chart needs no toolchain)
const HIST = {t: [], cpu: [], tpu: [], actors: [], running: []};
async function sampleMetrics() {
  try {
    const [s, a, t] = await Promise.all([
      J("/api/cluster_status"), J("/api/actors"),
      J("/api/tasks?limit=2000")]);
    const res = s.total_resources || {}, av = s.available_resources || {};
    const used = (r) => (res[r] ?? 0) - (av[r] ?? 0);
    HIST.t.push(Date.now() / 1000);
    HIST.cpu.push(used("CPU"));
    HIST.tpu.push(used("TPU"));
    HIST.actors.push(a.actors.filter(x => x.state === "ALIVE").length);
    HIST.running.push(t.tasks.filter(x => x.state === "RUNNING").length);
    for (const k in HIST) if (HIST[k].length > 240) HIST[k].shift();
  } catch (e) {}
}
setInterval(sampleMetrics, 5000);
sampleMetrics();

function lineChart(title, xs, ys, color) {
  const W = 540, H = 120, P = 28;
  if (ys.length < 2)
    return `<div class="tile" style="width:${W}px"><div class="k">` +
      `${esc(title)}</div><div class="hint">gathering…</div></div>`;
  const ymax = Math.max(1e-9, ...ys), ymin = Math.min(0, ...ys);
  const x0 = xs[0], x1 = xs[xs.length - 1] || x0 + 1;
  const px = (x) => P + (W - P - 8) * (x - x0) / Math.max(1e-9, x1 - x0);
  const py = (y) => H - 18 - (H - 30) * (y - ymin) /
    Math.max(1e-9, ymax - ymin);
  const pts = xs.map((x, i) => `${px(x).toFixed(1)},${py(ys[i]).toFixed(1)}`)
    .join(" ");
  const last = ys[ys.length - 1];
  const span = Math.round(x1 - x0);
  return `<div class="tile" style="width:${W}px">` +
    `<div class="k">${esc(title)} <span style="float:right">now ` +
    `<b>${esc(last)}</b> · peak ${esc(ymax)} · last ${span}s</span></div>` +
    `<svg width="${W - 24}" height="${H}" role="img">` +
    `<line x1="${P}" y1="${py(ymin)}" x2="${W - 8}" y2="${py(ymin)}" ` +
    `stroke="var(--line)"/>` +
    `<line x1="${P}" y1="${py(ymax)}" x2="${W - 8}" y2="${py(ymax)}" ` +
    `stroke="var(--line)" stroke-dasharray="3 3"/>` +
    `<text x="2" y="${py(ymax) + 4}" font-size="10" ` +
    `fill="var(--muted)">${esc(ymax)}</text>` +
    `<text x="2" y="${py(ymin) + 4}" font-size="10" ` +
    `fill="var(--muted)">${esc(ymin)}</text>` +
    `<polyline points="${pts}" fill="none" stroke="${color}" ` +
    `stroke-width="1.5"/></svg></div>`;
}

async function renderMetrics() {
  return `<div class="hint">sampled every 5 s in-page (Prometheus ` +
    `scrape endpoint: /metrics)</div><div class="tiles">` +
    lineChart("CPUs in use", HIST.t, HIST.cpu, "var(--accent)") +
    lineChart("TPUs in use", HIST.t, HIST.tpu, "var(--warn)") +
    lineChart("live actors", HIST.t, HIST.actors, "var(--ok)") +
    lineChart("running tasks", HIST.t, HIST.running, "var(--accent)") +
    `</div>`;
}

// ---- Timeline: task swimlanes per worker from the GCS task table
// (same data `ray-tpu timeline` exports as a chrome trace)
async function renderTimeline() {
  const d = await J("/api/tasks?limit=2000");
  const done = d.tasks.filter(t => t.start_time);
  if (!done.length)
    return `<div class="hint">no task events yet</div>`;
  const now = Date.now() / 1000;
  const t1 = Math.max(...done.map(t => t.end_time || now));
  const t0 = Math.max(Math.min(...done.map(t => t.start_time)), t1 - 120);
  const lanes = new Map();
  for (const t of done) {
    if ((t.end_time || now) < t0) continue;
    const w = (t.worker_id || "?").slice(0, 12);
    if (!lanes.has(w)) lanes.set(w, []);
    lanes.get(w).push(t);
  }
  const laneIds = [...lanes.keys()].slice(0, 16);
  const W = 1100, LH = 20, LX = 110;
  const px = (x) => LX + (W - LX - 8) * (x - t0) / Math.max(1e-9, t1 - t0);
  let rows = "";
  laneIds.forEach((w, i) => {
    const y = i * LH;
    rows += `<text x="2" y="${y + 14}" font-size="11" class="mono" ` +
      `fill="var(--ink2)">${esc(w)}</text>`;
    for (const t of lanes.get(w)) {
      const s = Math.max(t.start_time, t0), e = t.end_time || now;
      const wid = Math.max(2, px(e) - px(s));
      const color = t.state === "FAILED" ? "var(--bad)"
        : (t.state === "RUNNING" ? "var(--warn)" : "var(--accent)");
      rows += `<rect x="${px(s).toFixed(1)}" y="${y + 3}" ` +
        `width="${wid.toFixed(1)}" height="${LH - 7}" rx="2" ` +
        `fill="${color}" fill-opacity="0.75">` +
        `<title>${esc(t.name)} (${esc(t.state)}) ` +
        `${((e - s)).toFixed(3)}s</title></rect>`;
    }
  });
  const H = laneIds.length * LH + 24;
  return `<div class="hint">last ${(t1 - t0).toFixed(0)} s of task ` +
    `execution, one lane per worker (hover for name/duration; full ` +
    `chrome trace: <span class="mono">ray-tpu timeline</span>)</div>` +
    `<div class="tile" style="width:${W + 24}px"><svg width="${W}" ` +
    `height="${H}">${rows}` +
    `<text x="${LX}" y="${H - 4}" font-size="10" fill="var(--muted)">` +
    `${new Date(t0 * 1000).toLocaleTimeString()}</text>` +
    `<text x="${W - 70}" y="${H - 4}" font-size="10" ` +
    `fill="var(--muted)">` +
    `${new Date(t1 * 1000).toLocaleTimeString()}</text></svg></div>`;
}

async function renderNodes() {
  const d = await J("/api/nodes");
  return table(["node", "state", "address", "CPU free", "TPU free",
                "labels"],
    d.nodes.map(n => [
      `<span class="mono">${esc(n.node_id.slice(0, 12))}</span>`,
      badge(n.alive ? "ALIVE" : "DEAD"),
      `<span class="mono">${esc((n.address || []).join(":"))}</span>`,
      `${(n.available?.CPU ?? 0)} / ${(n.resources?.CPU ?? 0)}`,
      `${(n.available?.TPU ?? 0)} / ${(n.resources?.TPU ?? 0)}`,
      esc(JSON.stringify(n.labels || {}))]));
}

async function renderActors() {
  const d = await J("/api/actors");
  return table(["actor", "name", "state", "node", "restarts"],
    d.actors.map(a => [
      `<span class="mono">${esc(a.actor_id.slice(0, 12))}</span>`,
      esc(a.name || ""), badge(a.state),
      `<span class="mono">${esc((a.node_id || "").slice(0, 12))}</span>`,
      `${a.restarts ?? 0}/${a.max_restarts ?? 0}`]));
}

async function renderTasks() {
  // /api/tasks rows: {task_id, name, state, events: [{state, ts}, ...]}
  const d = await J("/api/tasks?limit=300");
  const items = d.tasks.slice(-120).reverse().map(t => {
    const ts = (t.events || []).map(e => e.ts);
    const dur = ts.length ? Math.max(...ts) - Math.min(...ts) : 0;
    return {id: t.task_id, name: t.name, state: t.state, dur};
  });
  const maxDur = Math.max(0.001, ...items.map(r => r.dur));
  return `<div class="hint">most recent tasks — bar = wall time ` +
    `(longest ${maxDur.toFixed(2)} s)</div>` +
    table(["task", "name", "state", "duration", ""],
      items.map(r => [
        `<span class="mono">${esc(r.id.slice(0, 12))}</span>`,
        esc(r.name), badge(r.state), `${r.dur.toFixed(3)} s`,
        `<span class="bar"><i style="width:${
           Math.max(2, 100 * r.dur / maxDur)}%"></i></span>`]));
}

// ---- Training: the performance plane's goodput ledger + step skew
// (GCS step table, docs/observability.md) — per-run MFU/goodput tiles,
// per-rank time buckets, and the recent cross-rank skew
async function renderTraining() {
  const d = await J("/api/training");
  const runs = d.runs || [];
  if (!runs.length)
    return `<div class="hint">no training runs have reported step ` +
      `stats yet (per-step phase clocks: ray_tpu.train.step_clock)` +
      `</div>`;
  const s = d.summary || {};
  const agg = s.aggregate || {};
  let html = "";
  if (s.run) {
    const tiles = [
      ["run", s.run], ["world", s.world],
      ["goodput", agg.goodput != null ?
        (100 * agg.goodput).toFixed(1) + "%" : "–"],
      ["MFU", agg.mfu != null ? (100 * agg.mfu).toFixed(2) + "%" : "–"],
      ["tokens/s", agg.tokens_per_s ?? "–"],
      ["steps", s.steps_seen ?? 0],
    ];
    html += `<div class="tiles">` + tiles.map(([k, v]) =>
      `<div class="tile"><div class="v">${esc(v)}</div>` +
      `<div class="k">${esc(k)}</div></div>`).join("") + `</div>`;
    const ranks = Object.entries(s.ranks || {});
    if (ranks.length) {
      html += table(["rank", "steps", "init (ms)", "compile (ms)",
                     "productive (ms)", "ckpt (ms)", "idle (ms)",
                     "goodput", "MFU"],
        ranks.map(([r, l]) => [
          esc(r), esc(l.steps ?? 0),
          (l.init_ms ?? 0).toFixed(0), (l.compile_ms ?? 0).toFixed(0),
          (l.productive_ms ?? 0).toFixed(0),
          (l.checkpoint_ms ?? 0).toFixed(0),
          (l.idle_ms ?? 0).toFixed(0),
          ((l.goodput ?? 0) * 100).toFixed(1) + "%",
          ((l.mfu ?? 0) * 100).toFixed(2) + "%"]));
    }
  }
  html += `<div class="hint">runs (stragglers flagged from ` +
    `median + k·MAD cross-rank skew — TRAIN_STRAGGLER in Events)</div>`;
  html += table(["run", "group", "world", "steps", "straggling ranks",
                 "worst recent skew"],
    runs.slice().reverse().map(r => {
      const skew = (r.skew || []).reduce((a, b) =>
        (b.skew_ms > (a?.skew_ms ?? -1) ? b : a), null);
      const strag = Object.keys(r.straggling || {});
      return [
        `<span class="mono">${esc(r.run)}</span>`, esc(r.group || ""),
        esc(r.world), esc(r.steps_seen),
        strag.length ? badge("rank " + strag.join(", rank ")) :
          badge("OK"),
        skew ? `+${skew.skew_ms.toFixed(1)} ms @ step ${skew.step}` :
          "–"];
    }));
  return html;
}

// ---- Traces: the request tracing plane's span table — one row per
// sampled request (root route, TTFT/TPOT vs SLO targets), click a
// trace id to expand its span tree inline (docs/observability.md)
let followTrace = null;
async function renderTraces() {
  const d = await J("/api/traces?limit=100");
  const s = d.stats || {};
  let html = `<div class="tiles">` + [
      ["traces (retained)", `${s.traces ?? 0} / ${s.traces_seen ?? 0}`],
      ["spans", s.spans ?? 0],
      ["dropped by rotation", s.dropped_traces ?? 0],
    ].map(([k, v]) =>
      `<div class="tile"><div class="v">${esc(v)}</div>` +
      `<div class="k">${esc(k)}</div></div>`).join("") + `</div>`;
  html += `<div class="hint">sampled request traces (CLI: ` +
    `<span class="mono">ray-tpu traces --slo-violations</span>, ` +
    `<span class="mono">ray-tpu trace &lt;id&gt;</span>)</div>`;
  html += table(["trace", "time", "route", "spans", "TTFT (ms)",
                 "TPOT (ms)", "SLO", "status"],
    (d.traces || []).map(t => [
      `<a class="tracelink mono" data-tid="${esc(t.trace_id)}">` +
      `${esc(t.trace_id.slice(0, 16))}</a>`,
      t.start ? new Date(t.start * 1000).toLocaleTimeString() : "–",
      esc(t.route || t.name || ""), esc(t.nspans),
      t.ttft_ms != null ? t.ttft_ms.toFixed(1) : "–",
      t.tpot_ms != null ? t.tpot_ms.toFixed(2) : "–",
      t.slo_ok == null ? badge("–")
        : (t.slo_ok ? badge("OK")
           : badge("VIOLATED " + (t.slo_violated || []).join(","))),
      badge(t.status || "?")]));
  if (followTrace) {
    const td = await J(`/api/traces/${encodeURIComponent(followTrace)}`);
    const spans = (td.trace || {}).spans || [];
    const t0 = Math.min(...spans.map(sp => sp.start || 0));
    html += `<div class="hint">spans of <b class="mono">` +
      `${esc(followTrace.slice(0, 16))}</b> — ` +
      `<a class="tracelink" data-tid="">close</a></div>`;
    html += table(["+t (ms)", "span", "kind", "dur (ms)", "process",
                   "status", "detail"],
      spans.map(sp => [
        ((sp.start - t0) * 1000).toFixed(1),
        `<span class="mono">${esc(sp.name)}</span>`, esc(sp.kind),
        (sp.dur_ms ?? 0).toFixed(2),
        `<span class="mono">${esc((sp.worker_id || sp.source || "")
           .slice(0, 10))}</span>`,
        badge(sp.status || "ok"),
        esc(["bytes", "npages", "num_tokens", "error_type"]
          .filter(k => sp[k] != null).map(k => `${k}=${sp[k]}`)
          .join(" "))]));
  }
  return html;
}
document.addEventListener("click", (e) => {
  const a = e.target.closest("a.tracelink[data-tid]");
  if (a) { followTrace = a.dataset.tid || null; refresh(); }
});

async function renderJobs() {
  const d = await J("/api/jobs");
  let html = table(["job", "status", "entrypoint", "logs"],
    d.jobs.map(j => [
      `<span class="mono">${esc(j.submission_id)}</span>`,
      badge(j.status), `<span class="mono">${esc(j.entrypoint)}</span>`,
      // data attribute + delegated listener: a user-chosen submission_id
      // must never be spliced into inline JS (XSS sink)
      `<a class="joblink" data-sid="${esc(j.submission_id)}">tail</a>`]));
  if (followJob) {
    html += `<div class="hint">tailing logs of <b>${esc(followJob)}</b> ` +
      `(streams as the job writes) — ` +
      `<a class="joblink" data-sid="">stop</a></div>` +
      `<div id="log"></div>`;
  }
  return html;
}

async function renderServe() {
  const d = await J("/api/serve/applications");
  const deps = Object.entries(d.deployments || {});
  return table(["deployment", "status", "replicas", "route"],
    deps.map(([name, s]) => [
      esc(name), badge(s.status || s.state || "?"),
      `${s.running_replicas ?? s.replicas ?? "?"} / ` +
      `${s.target_replicas ?? s.num_replicas ?? "?"}`,
      `<span class="mono">/${esc(name)}</span>`]));
}

async function renderPGs() {
  // API shape: {"placement_groups": {pg_id: {state, strategy, bundles}}}
  const d = await J("/api/placement_groups");
  return table(["placement group", "state", "strategy", "bundles"],
    Object.values(d.placement_groups || {}).map(pg => [
      `<span class="mono">${esc((pg.pg_id || "").slice(0, 12))}</span>`,
      badge(pg.state), esc(pg.strategy || ""),
      esc(JSON.stringify(pg.bundles || []))]));
}

async function renderEvents() {
  const d = await J("/api/events?limit=200");
  return `<div class="hint">typed cluster lifecycle events (filters: ` +
    `?type=&severity=&node_id=&worker_id= — crash dossiers at ` +
    `<span class="mono">/api/dossiers</span>)</div>` +
    table(["time", "severity", "type", "source", "node", "worker",
           "message"],
    d.events.slice().reverse().map(e => [
      new Date(e.ts * 1000).toLocaleTimeString(),
      badge(e.severity), esc(e.type || e.label), esc(e.source),
      `<span class="mono">${esc((e.node_id || "").slice(0, 10))}</span>`,
      `<span class="mono">${esc((e.worker_id || "").slice(0, 10))}</span>`,
      esc(e.message)]));
}

window.tailJob = (sid) => { followJob = sid || null; logOffset = 0;
                            refresh(); };
document.addEventListener("click", (e) => {
  const a = e.target.closest("a.joblink[data-sid]");
  if (a) tailJob(a.dataset.sid);
});

const RENDER = {"Overview": renderOverview, "Metrics": renderMetrics,
  "Nodes": renderNodes, "Actors": renderActors, "Tasks": renderTasks,
  "Timeline": renderTimeline, "Training": renderTraining,
  "Traces": renderTraces, "Jobs": renderJobs, "Serve": renderServe,
  "Placement Groups": renderPGs, "Events": renderEvents};

async function pollLog(g) {
  if (tab !== "Jobs" || !followJob) return;
  const d = await J(`/api/jobs/${encodeURIComponent(followJob)}` +
                    `/logs?offset=${logOffset}`);
  if (g !== gen) return;   // a newer refresh owns the log pane now
  const el = $("log");
  if (el && d.text) {
    el.textContent += d.text;
    el.scrollTop = el.scrollHeight;
  }
  logOffset = d.offset ?? logOffset;
}

let gen = 0;   // invalidates in-flight refreshes on tab switch / re-entry
async function refresh() {
  const g = ++gen;
  try {
    const html = await RENDER[tab]();
    if (g !== gen) return;   // superseded: don't overwrite newer content
    const logEl = $("log");
    const keep = logEl ? logEl.textContent : "";
    $("main").innerHTML = html;
    if ($("log") && keep) { $("log").textContent = keep;
                            $("log").scrollTop = $("log").scrollHeight; }
    await pollLog(g);
    $("tick").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    if (g === gen) $("tick").textContent = "refresh failed: " + e;
  }
}

function setTab(t) {
  tab = t; location.hash = t;
  document.querySelectorAll("nav button").forEach(b =>
    b.classList.toggle("active", b.textContent === t));
  $("main").innerHTML = "loading…";
  refresh();
}

$("nav").innerHTML = TABS.map(t => `<button>${t}</button>`).join("");
document.querySelectorAll("nav button").forEach(b =>
  b.addEventListener("click", () => setTab(b.textContent)));
J("/api/version").then(v =>
  $("meta").textContent = `v${v.version}`).catch(() => {});
setTab(TABS.includes(tab) ? tab : "Overview");
timer = setInterval(refresh, 2000);
</script>
</body>
</html>
"""
