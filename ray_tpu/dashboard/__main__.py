"""Standalone dashboard daemon: `python -m ray_tpu.dashboard --gcs ...`."""

import argparse
import threading

from ray_tpu.dashboard.head import DashboardHead


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8265)
    args = parser.parse_args()
    head = DashboardHead((args.gcs_host, args.gcs_port),
                         host=args.host, port=args.port)
    host, port = head.start()
    print(f"dashboard serving at http://{host}:{port}", flush=True)
    threading.Event().wait()


if __name__ == "__main__":
    main()
