"""Dashboard backend: HTTP JSON API + Prometheus scrape endpoint.

Analog of /root/reference/python/ray/dashboard/ (head.py,
http_server_head.py aiohttp app + modules/). No React frontend is shipped;
the JSON API mirrors the reference module routes (nodes, actors, jobs,
tasks, cluster_status) and `/metrics` serves Prometheus text exposition —
the piece Grafana actually scrapes.
"""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard  # noqa: F401

__all__ = ["DashboardHead", "start_dashboard"]
