"""Single-file dashboard UI served at `/`.

Stands in for the reference's React SPA (/root/reference/dashboard/client,
~30k LoC TS): one dependency-free HTML page that polls the same REST
endpoints the SPA would (nodes / cluster status / actors / jobs / serve)
and renders live tables.  The REST JSON remains the programmatic surface.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font: 13px/1.5 system-ui, sans-serif; margin: 2rem; color: #222; }
  h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.6rem; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: 3px 10px 3px 0;
           border-bottom: 1px solid #e5e5e5; font-variant-numeric: tabular-nums; }
  th { color: #666; font-weight: 600; }
  .ok { color: #0a7d33; } .bad { color: #c0392b; }
  #meta { color: #666; }
  code { background: #f5f5f5; padding: 1px 4px; border-radius: 3px; }
</style>
</head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="meta">loading…</div>
<h2>Cluster</h2><div id="cluster"></div>
<h2>Nodes</h2><div id="nodes"></div>
<h2>Actors</h2><div id="actors"></div>
<h2>Jobs</h2><div id="jobs"></div>
<h2>Serve</h2><div id="serve"></div>
<script>
const esc = (s) => String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
const fmt = (o) => esc(typeof o === "object" ?
    Object.entries(o || {}).map(([k, v]) => k + ": " +
        (typeof v === "number" && !Number.isInteger(v) ? v.toFixed(1) : v))
        .join(", ") : String(o));
function table(rows, cols) {
  if (!rows || !rows.length) return "<em>none</em>";
  let h = "<table><tr>" + cols.map(c => "<th>" + c[0] + "</th>").join("")
          + "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c => "<td>" + c[1](r) + "</td>").join("") + "</tr>";
  return h + "</table>";
}
const alive = a => a ? '<span class="ok">ALIVE</span>'
                     : '<span class="bad">DEAD</span>';
async function refresh() {
  try {
    const [ver, cs, nodes, actors, jobs, serve] = await Promise.all([
      "/api/version", "/api/cluster_status", "/api/nodes", "/api/actors",
      "/api/jobs", "/api/serve/applications",
    ].map(u => fetch(u).then(r => r.json())));
    document.getElementById("meta").textContent =
      "version " + ver.version + " — refreshed " +
      new Date().toLocaleTimeString();
    document.getElementById("cluster").innerHTML = table([cs], [
      ["alive nodes", r => r.alive_nodes],
      ["dead nodes", r => r.dead_nodes],
      ["total", r => fmt(r.total_resources)],
      ["available", r => fmt(r.available_resources)]]);
    document.getElementById("nodes").innerHTML = table(nodes.nodes, [
      ["node", r => "<code>" + r.node_id.slice(0, 12) + "</code>"],
      ["state", r => alive(r.alive)],
      ["address", r => r.address.join(":")],
      ["resources", r => fmt(r.resources)],
      ["available", r => fmt(r.available)]]);
    document.getElementById("actors").innerHTML = table(actors.actors, [
      ["actor", r => "<code>" + r.actor_id.slice(0, 12) + "</code>"],
      ["name", r => esc(r.name || "")],
      ["state", r => r.state === "ALIVE" ?
          '<span class="ok">ALIVE</span>' : esc(r.state)],
      ["restarts", r => r.restarts || 0],
      ["node", r => r.node_id ? r.node_id.slice(0, 12) : ""]]);
    document.getElementById("jobs").innerHTML = table(jobs.jobs, [
      ["job", r => "<code>" + (r.submission_id || r.job_id ||
                               "").slice(0, 16) + "</code>"],
      ["status", r => esc(r.status)],
      ["entrypoint", r => esc(r.entrypoint || "")]]);
    const sd = Object.entries(serve.deployments || {}).map(
        ([name, s]) => ({name, ...s}));
    document.getElementById("serve").innerHTML = table(sd, [
      ["deployment", r => esc(r.name)],
      ["status", r => r.status === "HEALTHY" ?
          '<span class="ok">HEALTHY</span>' : esc(r.status)],
      ["replicas", r => r.running_replicas + "/" + r.target_replicas],
      ["version", r => esc("v" + r.version)]]);
  } catch (e) {
    document.getElementById("meta").textContent = "refresh failed: " + e;
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
