"""ctypes binding for the native cluster scheduler (csrc/scheduler.cc).

Binding layer in the spirit of the reference's _raylet.pyx over
ClusterResourceScheduler (/root/reference/src/ray/raylet/scheduling/
cluster_resource_scheduler.h:45).  Resources cross the ABI as fixed-point
milli-units packed into "name=milli;..." strings; if the .so isn't built, a
pure-Python ClusterScheduler with identical semantics takes over (same
tests run against both).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Optional

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libscheduler.so")
MILLI = 1000


def _pack(resources: Dict[str, float]) -> bytes:
    return ";".join(
        f"{k}={int(round(v * MILLI))}" for k, v in sorted(resources.items())
    ).encode()


def _load_lib():
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.sched_create.restype = ctypes.c_void_p
    lib.sched_create.argtypes = [ctypes.c_double, ctypes.c_int]
    lib.sched_destroy.argtypes = [ctypes.c_void_p]
    lib.sched_update_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int]
    lib.sched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.sched_num_nodes.restype = ctypes.c_int64
    lib.sched_num_nodes.argtypes = [ctypes.c_void_p]
    lib.sched_best_node.restype = ctypes.c_int
    lib.sched_best_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int64, ctypes.c_char_p,
                                    ctypes.c_int64]
    lib.sched_feasible_anywhere.restype = ctypes.c_int
    lib.sched_feasible_anywhere.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


_lib = _load_lib()


class NativeClusterScheduler:
    """Hybrid/spread node selection over the native node table."""

    def __init__(self, spill_threshold: float = 0.5, top_k: int = 1):
        self._h = _lib.sched_create(spill_threshold, top_k)
        self._seed = 0
        self._lock = threading.Lock()

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                _lib.sched_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def update_node(self, node_id: str, total: Dict[str, float],
                    available: Dict[str, float], alive: bool = True) -> None:
        _lib.sched_update_node(self._h, node_id.encode(), _pack(total),
                               _pack(available), int(alive))

    def remove_node(self, node_id: str) -> None:
        _lib.sched_remove_node(self._h, node_id.encode())

    def num_nodes(self) -> int:
        return int(_lib.sched_num_nodes(self._h))

    def best_node(self, demand: Dict[str, float],
                  local_id: Optional[str] = None,
                  spread: bool = False) -> Optional[str]:
        out = ctypes.create_string_buffer(256)
        with self._lock:
            seed = self._seed
            self._seed += 1
        ok = _lib.sched_best_node(self._h, _pack(demand),
                                  (local_id or "").encode(), int(spread),
                                  seed, out, len(out))
        return out.value.decode() if ok else None

    def feasible_anywhere(self, demand: Dict[str, float]) -> bool:
        return bool(_lib.sched_feasible_anywhere(self._h, _pack(demand)))


class PyClusterScheduler:
    """Pure-Python fallback with the same semantics (and test suite)."""

    def __init__(self, spill_threshold: float = 0.5, top_k: int = 1):
        self.spill_threshold = spill_threshold
        self.top_k = max(top_k, 1)
        self._nodes: Dict[str, dict] = {}
        self._seed = 0
        self._lock = threading.Lock()

    @staticmethod
    def _milli(res: Dict[str, float]) -> Dict[str, int]:
        return {k: int(round(v * MILLI)) for k, v in res.items()}

    def update_node(self, node_id, total, available, alive=True):
        with self._lock:
            self._nodes[node_id] = {"total": self._milli(total),
                                    "available": self._milli(available),
                                    "alive": alive}

    def remove_node(self, node_id):
        with self._lock:
            self._nodes.pop(node_id, None)

    def num_nodes(self):
        with self._lock:
            return len(self._nodes)

    @staticmethod
    def _feasible(node, demand, against_total):
        cap = node["total"] if against_total else node["available"]
        return all(cap.get(k, 0) >= v for k, v in demand.items() if v > 0)

    @staticmethod
    def _utilization(node, demand):
        worst = 0.0
        for name, tot in node["total"].items():
            if tot <= 0:
                continue
            used = tot - node["available"].get(name, 0) + demand.get(name, 0)
            worst = max(worst, used / tot)
        return worst

    def best_node(self, demand, local_id=None, spread=False):
        demand = self._milli(demand)
        with self._lock:
            nodes = {k: dict(v) for k, v in self._nodes.items()}
            seed = self._seed
            self._seed += 1
        if not spread and local_id and local_id in nodes:
            n = nodes[local_id]
            if n["alive"] and self._feasible(n, demand, False) and \
                    self._utilization(n, demand) <= self.spill_threshold:
                return local_id
        scored = sorted(
            (self._utilization(n, demand), nid)
            for nid, n in nodes.items()
            if n["alive"] and self._feasible(n, demand, False))
        if not scored:
            return None
        k = min(self.top_k, len(scored))
        return scored[seed % k][1]

    def feasible_anywhere(self, demand):
        demand = self._milli(demand)
        with self._lock:
            return any(n["alive"] and self._feasible(n, demand, True)
                       for n in self._nodes.values())


def make_scheduler(spill_threshold: float = 0.5, top_k: int = 1):
    """Native scheduler when the .so is built, Python fallback otherwise."""
    if _lib is not None:
        return NativeClusterScheduler(spill_threshold, top_k)
    return PyClusterScheduler(spill_threshold, top_k)


def native_available() -> bool:
    return _lib is not None
