"""Staleness guard for the prebuilt native binaries in ray_tpu/_core/.

The repo ships built ELF artifacts (cpp_worker, libshmstore.so,
libscheduler.so, pycodec_tool) so a fresh checkout works without a
toolchain — but after any csrc/ edit a committed binary silently goes
stale and runtime behavior diverges from source.  `make -C csrc` writes
a stamp (`.src_sha256`, the hash of every csrc source) next to the
binaries; ensure_fresh() recomputes that hash and, on mismatch, rebuilds
before the binary is spawned/loaded (or warns when no toolchain exists).

Importable standalone (no package imports): the Makefile invokes
`python3 buildcheck.py --write-stamp` after a successful build.
"""
import hashlib
import os
import subprocess
import threading

_CORE_DIR = os.path.dirname(os.path.abspath(__file__))
_STAMP = os.path.join(_CORE_DIR, ".src_sha256")

_lock = threading.Lock()
_checked = False


def _csrc_dir() -> str:
    repo = os.path.dirname(os.path.dirname(_CORE_DIR))
    return os.path.join(repo, "csrc")


def source_hash():
    """Hash of every csrc source file, or None when the package is
    installed without its sources (nothing to be stale against)."""
    d = _csrc_dir()
    if not os.path.isdir(d):
        return None
    h = hashlib.sha256()
    for name in sorted(os.listdir(d)):
        if name.endswith((".cc", ".h")) or name == "Makefile":
            h.update(name.encode())
            with open(os.path.join(d, name), "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def write_stamp() -> None:
    digest = source_hash()
    if digest is not None:
        with open(_STAMP, "w") as f:
            f.write(digest)


def ensure_fresh(logger=None) -> None:
    """Verify the committed binaries match csrc/ sources; rebuild if not.

    Cheap (hashes ~15 small files) and runs at most once per process.
    A failed rebuild degrades to a loud warning rather than an error:
    the stale binary is still runnable, just possibly divergent.
    """
    global _checked
    with _lock:
        if _checked:
            return
        _checked = True
        want = source_hash()
        if want is None:
            return
        if _stamp_matches(want):
            return
        # Stale. Serialize the rebuild across PROCESSES too (several
        # raylets on one machine may spawn workers concurrently; two
        # parallel `make`s would race writing the same binaries).
        import fcntl
        lock_path = os.path.join(_CORE_DIR, ".build_lock")
        try:
            with open(lock_path, "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                # another process may have finished the rebuild while we
                # waited for the lock
                if _stamp_matches(want):
                    return
                subprocess.run(["make", "-C", _csrc_dir()], check=True,
                               capture_output=True, timeout=600)
                write_stamp()
        except Exception as exc:  # toolchain missing / compile error
            msg = ("ray_tpu/_core binaries are stale relative to csrc/ "
                   f"sources and rebuild failed ({exc}); runtime behavior "
                   "may diverge from source — run `make -C csrc`")
            if logger is not None:
                logger.warning(msg)
            else:
                import warnings
                warnings.warn(msg)


def _stamp_matches(want: str) -> bool:
    if not os.path.exists(_STAMP):
        return False
    with open(_STAMP) as f:
        return f.read().strip() == want


if __name__ == "__main__":
    import sys
    if "--write-stamp" in sys.argv:
        write_stamp()
    else:
        ensure_fresh()
