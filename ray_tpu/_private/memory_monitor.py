"""Host-memory monitor + OOM worker-killing policy.

TPU-native analog of the reference MemoryMonitor
(/root/reference/src/ray/common/memory_monitor.h:52 — kernel memory polling
at memory_monitor_refresh_ms) and the retriable-LIFO worker-killing policy
(src/ray/raylet/worker_killing_policy.h:30/60): when host usage crosses
memory_usage_threshold, the raylet kills the worker whose loss costs least
to recover — retriable task workers before actors, newest first — instead
of letting the kernel OOM-killer take out a daemon.

Test/chaos seam: ``memory_monitor_test_usage_path`` (a file holding a float
usage fraction) substitutes for the kernel counters, the analog of the
reference's fault-injecting test doubles.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import psutil

from ray_tpu._private.config import CONFIG
from ray_tpu._private.logging_utils import get_logger

logger = get_logger("memory_monitor")


def system_memory_usage_fraction() -> float:
    vm = psutil.virtual_memory()
    return (vm.total - vm.available) / vm.total


class MemoryMonitor:
    """Polls a usage source and fires ``on_breach(usage)`` when it crosses
    the configured threshold.  The caller (raylet) owns victim selection
    and re-arm pacing."""

    def __init__(self, on_breach: Callable[[float], None],
                 usage_fn: Optional[Callable[[], float]] = None):
        self.threshold = CONFIG.memory_usage_threshold
        self.refresh_s = CONFIG.memory_monitor_refresh_ms / 1000.0
        self._on_breach = on_breach
        test_path = CONFIG.memory_monitor_test_usage_path
        if usage_fn is not None:
            self._usage_fn = usage_fn
        elif test_path:
            self._usage_fn = lambda: _read_usage_file(test_path)
        else:
            self._usage_fn = system_memory_usage_fraction
        self.last_usage = 0.0
        self._source_warned = False

    @property
    def enabled(self) -> bool:
        return self.refresh_s > 0

    def poll_once(self) -> None:
        try:
            usage = float(self._usage_fn())
            self._source_warned = False
        except Exception:
            if not self._source_warned:
                # once per outage, not per poll: a dead memory source means
                # OOM protection is OFF and must not fail silently
                logger.exception("memory usage source failed; OOM "
                                 "protection inactive until it recovers")
                self._source_warned = True
            return
        self.last_usage = usage
        if usage >= self.threshold:
            self._on_breach(usage)


def _read_usage_file(path: str) -> float:
    try:
        with open(path) as f:
            return float(f.read().strip() or 0.0)
    except (OSError, ValueError):
        return 0.0


def pick_oom_victim(workers) -> Optional[str]:
    """Retriable-LIFO policy (worker_killing_policy.h:60): among active
    workers prefer killing a *task* worker (its work retries via lineage /
    submitter retry) over an actor worker (restart is heavier), and among
    equals the most recently started (least progress lost).  Idle workers
    are skipped — the idle trimmer reclaims those for free.

    ``workers`` is an iterable of (worker_id_hex, is_actor, started_at,
    is_active).  Returns a worker id or None."""
    candidates = [(wid, is_actor, started)
                  for wid, is_actor, started, active in workers if active]
    if not candidates:
        return None
    candidates.sort(key=lambda t: (t[1], -t[2]))  # tasks first, newest first
    return candidates[0][0]
