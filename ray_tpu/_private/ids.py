"""Binary identifiers for jobs, tasks, actors, objects, nodes, placement groups.

Mirrors the role of the reference's ID hierarchy
(/root/reference/src/ray/common/id.h: JobID 4B, ActorID 16B, TaskID 24B,
ObjectID 28B with embedded task id + return index) but with a simpler uniform
scheme: every ID is 16 random bytes except ObjectID, which embeds its parent
TaskID plus a 4-byte return/put index so ownership and lineage can be derived
from the ID alone — the property the reference relies on for reconstruction.
"""

from __future__ import annotations

import os

_UNIQUE_LEN = 16
_OBJECT_LEN = _UNIQUE_LEN + 4


class BaseID:
    __slots__ = ("_bytes",)
    LENGTH = _UNIQUE_LEN

    def __init__(self, value: bytes):
        if not isinstance(value, bytes) or len(value) != self.LENGTH:
            raise ValueError(
                f"{type(self).__name__} requires {self.LENGTH} bytes, got {value!r}")
        self._bytes = value

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.LENGTH))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.LENGTH)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.LENGTH

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._bytes))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ObjectID(BaseID):
    """TaskID (16B) + big-endian uint32 index.

    Index 0.. for task returns; puts use a per-worker counter offset by 2**31
    (cf. reference ObjectID::FromIndex, id.h).
    """

    LENGTH = _OBJECT_LEN
    _PUT_OFFSET = 1 << 31

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls.for_task_return(task_id, cls._PUT_OFFSET + put_index)

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_UNIQUE_LEN])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[_UNIQUE_LEN:], "big")

    def is_put(self) -> bool:
        return self.return_index() >= self._PUT_OFFSET
