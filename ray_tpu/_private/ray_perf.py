"""Core-runtime microbenchmark suite.

Reports the reference's nightly microbenchmark metrics (names from
/root/reference/python/ray/_private/ray_perf.py:93, run by
release/microbenchmark/run_microbenchmark.py) so the two runtimes can be
compared line by line: put/get ops/s against the shared-memory store,
task submission sync/async, actor call sync/async/concurrent, and
put-gigabytes bandwidth. Run via ``python -m ray_tpu._private.ray_perf``
or ``ray-tpu microbenchmark``; ``TESTS_TO_RUN=pattern`` filters.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np


def timeit(name: str, fn: Callable, multiplier: float = 1,
           *, warmup: int = 1, min_time: float = 2.0,
           results: Optional[List[Dict]] = None) -> List[Dict]:
    """Run fn repeatedly for ~min_time seconds; report multiplier*calls/s
    (same contract as the reference's ray_perf timeit)."""
    pattern = os.environ.get("TESTS_TO_RUN", "")
    if pattern and pattern not in name:
        return results if results is not None else []
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    entry = {"name": name, "ops_per_s": round(rate, 2),
             "calls": count, "seconds": round(dt, 3)}
    print(f"{name}: {rate:,.2f} per second")
    if results is not None:
        results.append(entry)
    return results if results is not None else [entry]


def main(min_time: float = 2.0) -> List[Dict]:
    import ray_tpu

    if ray_tpu.is_initialized():
        # attaching to a caller's cluster would drop the oversubscribed
        # CPU slots the nested benchmarks need — and the finally-block
        # would tear down a cluster this function doesn't own
        raise RuntimeError(
            "ray_perf.main() needs to own the cluster; call it before "
            "ray_tpu.init() (or after shutdown())")
    results: List[Dict] = []
    # logical CPUs (scheduling slots), deliberately oversubscribed —
    # the nested-task benchmarks need slots beyond the gang actors' own,
    # but capped: every slot can become a worker process, and more
    # workers than ~4x the physical cores thrash instead of overlapping
    # (each also costs a ~2 s spawn on this box)
    ray_tpu.init(num_cpus=max(min((os.cpu_count() or 1) * 4, 16), 4),
                 object_store_memory=512 * 1024 * 1024)
    try:
        t = lambda n, f, m=1, warmup=1: timeit(  # noqa: E731
            n, f, m, warmup=warmup, min_time=min_time, results=results)

        value = ray_tpu.put(0)
        t("single client get calls (Plasma Store)",
          lambda: ray_tpu.get(value))
        t("single client put calls (Plasma Store)",
          lambda: ray_tpu.put(0))

        arr = np.zeros(16 * 1024 * 1024 // 8, dtype=np.int64)  # 16 MiB
        gib = arr.nbytes / (1024 ** 3)
        t("single client put gigabytes", lambda: ray_tpu.put(arr), gib)

        @ray_tpu.remote
        def small_value():
            return 0

        t("single client tasks sync",
          lambda: ray_tpu.get(small_value.remote()))
        # concurrency benches need several warmup batches: each new lease
        # spawns a worker (~2 s of CPU on this 1-core box), and a spawn
        # landing inside the timed window measures process startup, not
        # the task path.  The reference's 16-core runners spawn in ms and
        # never see this.
        t("single client tasks async",
          lambda: ray_tpu.get([small_value.remote() for _ in range(100)]),
          100, warmup=10)

        @ray_tpu.remote
        class Actor:
            def small_value(self):
                return 0

            def small_value_batch(self, n):
                # submit n nested tasks (reference Actor.small_value_batch)
                import ray_tpu as rt
                return rt.get([small_value.remote() for _ in range(n)])

        # release each actor's worker before starting the next section —
        # unlike the reference's 16-core runners this box may have 1 core
        a = Actor.remote()
        t("1:1 actor calls sync",
          lambda: ray_tpu.get(a.small_value.remote()))
        ray_tpu.kill(a)
        a2 = Actor.remote()
        t("1:1 actor calls async",
          lambda: ray_tpu.get([a2.small_value.remote() for _ in range(100)]),
          100)
        ray_tpu.kill(a2)
        a3 = Actor.options(max_concurrency=16).remote()
        t("1:1 actor calls concurrent",
          lambda: ray_tpu.get([a3.small_value.remote() for _ in range(100)]),
          100)
        ray_tpu.kill(a3)

        n_actors = 2
        n_nested = 20
        gang = [Actor.remote() for _ in range(n_actors)]
        t("multi client tasks async",
          lambda: ray_tpu.get(
              [g.small_value_batch.remote(n_nested) for g in gang]),
          n_nested * n_actors, warmup=5)
        for g in gang:
            ray_tpu.kill(g)
    finally:
        ray_tpu.shutdown()
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    main(min_time=float(os.environ.get("PERF_MIN_TIME", "2.0")))
